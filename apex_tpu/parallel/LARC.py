"""LARC — layer-wise adaptive rate control.

Reference: ``apex/parallel/LARC.py :: class LARC`` wraps any optimizer and
rescales each param's gradient so the effective layer lr is
``trust_coefficient * ||p|| / (||g|| + weight_decay * ||p||)`` (clipped at
the base lr when ``clip=True``). Same contract here: wrap one of the fused
optimizers; grads are rescaled per leaf, then the inner optimizer steps.
"""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import f32


class LARC:
    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params: Any):
        return self.optim.init(params)

    def step(self, grads: Any, params: Any, state, *, lr=None,
             weight_decay=None, found_inf=None, **kw) -> Tuple[Any, Any]:
        base_lr = f32(lr if lr is not None else self.optim.lr)
        wd = f32(weight_decay if weight_decay is not None
                 else getattr(self.optim, "weight_decay", 0.0))
        tc, eps = f32(self.trust_coefficient), f32(self.eps)

        def rescale(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            g_norm = jnp.sqrt(jnp.sum(g32 * g32))
            adaptive_lr = tc * p_norm / (g_norm + p_norm * wd + eps)
            if self.clip:
                # effective layer lr = min(adaptive, base): grads scaled by
                # min(adaptive/base, 1), inner step applies base
                factor = jnp.minimum(adaptive_lr / base_lr, 1.0)
            else:
                # effective layer lr = base * adaptive (reference multiplies
                # the grad by adaptive_lr directly)
                factor = adaptive_lr
            # reference folds the decay into the grad BEFORE rescaling (so
            # decay is also trust-ratio-scaled) and zeroes the group's wd;
            # zero-norm leaves (frozen/unused) are left COMPLETELY untouched
            nonzero = (p_norm > 0) & (g_norm > 0)
            return jnp.where(nonzero,
                             (g32 + wd * p32) * factor, g32).astype(g.dtype)

        grads = jax.tree.map(rescale, grads, params)
        return self.optim.step(grads, params, state, lr=lr,
                               weight_decay=0.0, found_inf=found_inf, **kw)

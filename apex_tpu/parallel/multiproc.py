"""Multi-host bootstrap.

Reference: ``apex/parallel/multiproc.py`` — a pre-torchrun one-node
process launcher (one process per GPU). JAX on TPU is single-controller
per host and multi-host jobs rendezvous through
``jax.distributed.initialize``; there is nothing to fork locally. This
module keeps the entry point and maps it onto the JAX bootstrap.

Usage (one invocation per host, e.g. under a pod launcher)::

    python -m apex_tpu.parallel.multiproc train.py --args...
"""

import runpy
import sys

import jax


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Initialize multi-host JAX (env-driven when args are None)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(1)
    initialize_distributed()
    script = sys.argv[1]
    sys.argv = sys.argv[1:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()

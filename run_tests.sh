#!/usr/bin/env bash
# One-command test runner (reference: ``tests/L0/run_test.py`` — the
# upstream entry point CI and contributors invoke). Tiers:
#
#   ./run_tests.sh          # L0: unit/integration suite (CPU, 8 virtual
#                           #     devices via tests/conftest.py)
#   ./run_tests.sh L1       # L1: loss-curve parity sweeps (slower)
#   ./run_tests.sh all      # both
#
# The suite forces the CPU backend inside conftest.py (the axon env pins
# JAX_PLATFORMS at interpreter start, so pytest must be run through this
# entry or plain `python -m pytest` — never with JAX_PLATFORMS exported).
set -euo pipefail
cd "$(dirname "$0")"
tier="${1:-L0}"
shift || true
case "$tier" in
  L0)  exec python -m pytest tests/L0 -q "$@" ;;
  L1)  exec python -m pytest tests/L1 -q "$@" ;;
  all) exec python -m pytest tests -q "$@" ;;
  *)   echo "usage: $0 [L0|L1|all] [pytest args...]" >&2; exit 2 ;;
esac

#!/usr/bin/env bash
# One-command test runner (reference: ``tests/L0/run_test.py`` — the
# upstream entry point CI and contributors invoke). Tiers:
#
#   ./run_tests.sh          # L0: unit/integration suite (CPU, 8 virtual
#                           #     devices via tests/conftest.py)
#   ./run_tests.sh L1       # L1: loss-curve parity sweeps (slower)
#   ./run_tests.sh all      # both
#   ./run_tests.sh quick    # fast high-signal subset (-m quick) for the
#                           #     inner loop; full tier stays in CI
#   ./run_tests.sh chaos    # deterministic fault-injection tier for the
#                           #     serving engine (-m chaos): pinned and
#                           #     randomized fault schedules, typed
#                           #     outcomes, pool invariants audited
#                           #     after every tick, bit-identity of
#                           #     unaffected streams. Runs fully traced
#                           #     and dumps the Perfetto JSONL trace to
#                           #     $APEX_CHAOS_TRACE_OUT (defaulted below;
#                           #     CI uploads it as an artifact)
#   ./run_tests.sh gate     # L1 loss-curve gate: amp levels AND the
#                           #     reduced-precision optimizer-state modes
#                           #     (bf16 m, fused cast-out) must track the
#                           #     fp32 golden curve, and the quantized
#                           #     serving tiers (w8 / kv8 / w8+kv8) must
#                           #     track the trained fp32 eval-NLL curve
#                           #     — run on every PR
#   ./run_tests.sh lint     # apxlint, all six tiers: AST contract
#                           #     checks (kernel aliasing, collectives,
#                           #     AMP lists, hygiene), the VMEM budget
#                           #     pass, the jaxpr trace tier (APX5xx)
#                           #     over the entry registry, the cost
#                           #     tier (APX6xx byte budgets), the
#                           #     sharding tier (APX7xx partition-rule
#                           #     contracts), the determinism tier
#                           #     (APX8xx serving-stack race/ordering +
#                           #     fault-contract coverage), and the
#                           #     scaling tier (APX9xx mesh-sweep
#                           #     scale-invariance, per-shape trace
#                           #     time reported on stderr) — blocking
#                           #     in CI, with a combined wall-time
#                           #     budget enforced so the gate stays
#                           #     fast enough to run on every push
#
# The suite forces the CPU backend inside conftest.py (the axon env pins
# JAX_PLATFORMS at interpreter start, so pytest must be run through this
# entry or plain `python -m pytest` — never with JAX_PLATFORMS exported).
set -euo pipefail
cd "$(dirname "$0")"
tier="${1:-L0}"
shift || true
case "$tier" in
  L0)    exec python -m pytest tests/L0 -q "$@" ;;
  L1)    exec python -m pytest tests/L1 -q "$@" ;;
  all)   exec python -m pytest tests -q "$@" ;;
  quick) # the -m quick subset, then a few-arrival smoke of the
         # seeded-Poisson serving bench (tiny model, chat mix only via
         # APEX_BENCH_SCENARIOS) plus the multi-tenant adversarial
         # mix, so scheduler-policy regressions surface in the inner
         # loop, not first in CI
         python -m pytest tests -q -m quick "$@"
         echo "quick: Poisson serving-bench smoke (chat mix)" >&2
         env APEX_BENCH_SCENARIOS=chat python bench.py \
             gpt_serving_scenarios
         echo "quick: multi-tenant serving smoke (adversarial mix)" >&2
         exec python bench.py serving_multitenant ;;
  chaos) # per-seed trace dumps land next to this path (a tag + seed
         # suffix is spliced in before the extension); set it empty to
         # disable the dump
         : "${APEX_CHAOS_TRACE_OUT=$(mktemp -d)/apex_chaos_trace.jsonl}"
         export APEX_CHAOS_TRACE_OUT
         echo "chaos traces: ${APEX_CHAOS_TRACE_OUT:-disabled}" >&2
         exec python -m pytest tests -q -m chaos "$@" ;;
  gate)  exec python -m pytest tests/L1/test_loss_curve_parity.py \
             tests/L1/test_quant_eval_parity.py -q "$@" ;;
  lint)  # combined AST + VMEM + trace + cost + sharding + determinism
         # + scaling tiers, under a wall-time budget: a slow lint gate
         # stops being run, so exceeding the budget is itself a failure
         # (trim the entry registry or sweep grid — the per-shape
         # scaling timings on stderr say where the time goes)
         budget=90
         start=$SECONDS
         python -m apex_tpu.lint apex_tpu tests --trace --cost \
             --sharding --determinism --scaling "$@"
         elapsed=$(( SECONDS - start ))
         if (( elapsed > budget )); then
           echo "apxlint: combined run took ${elapsed}s," \
                "budget is ${budget}s" >&2
           exit 1
         fi ;;
  *)     echo "usage: $0 [L0|L1|all|quick|gate|lint] [pytest args...]" >&2
         exit 2 ;;
esac

#!/usr/bin/env python
"""BASELINE benchmark suite (BASELINE.md / BASELINE.json).

Prints one JSON line per config. The NORTH-STAR headline (BERT-Large
pretrain, amp O2 + FusedAdam, samples/sec/chip) runs FIRST — so a
budget/timeout death can't lose the contract metric — and its line is
RE-EMITTED LAST so the driver's parse-the-tail convention lands on it.
Execution order (see ``ORDER``): headline, compiled-kernel parity,
flash attention (d=64 seq 2048/4096 + the d=128 MXU-full line),
LN/RMS microbench, FusedAdam / FusedLAMB on the BERT-Large param set,
the flat-vs-tree 1024-small-tensor pair, DDP BERT, TP GPT. A global
wall budget (``BENCH_BUDGET_S``, default 45 min) with per-config caps
guarantees the run finishes; skipped/capped configs emit marker lines.

Timing methodology (see axon-relay pitfall): ``jax.block_until_ready``
does not reliably synchronize through the relay, so every measurement
ends in a ``float()`` fetch of a value data-dependent on the whole
chain, and per-iteration time is the DIFFERENCE of two measured chain
lengths (fixed dispatch+fetch cost cancels) — see ``timed`` for the
single-program chained scheme, the two-program many-leaf scheme, and
the donating state protocol. ``vs_baseline`` compares against the
latest driver-written ``BENCH_r*.json`` round, ``vs_best`` against the
best round ever (the reference publishes no numbers — BASELINE.md);
``checked`` re-measures once when a result lands >3x off its best
recorded value.

Same-process A/B (``ab_kernels`` config / ``python bench.py ab``):
cross-process repeats of one program drift ±15-20% through the relay,
so sub-20% claims are only resolvable by compiling both variants in ONE
process and interleaving their samples A,B,A,B,... — see ``bench_ab``
and the ``AB_PAIRS`` registry (flash d=64 exp2 / bf16-p / block-cap
variants, fused-vs-jnp LN h1024).

Serving configs run with a live ``Tracer`` and report its
registry-derived tick-clock percentiles (``ttft_p50/p95/p99``,
``itl_p50/p95/p99``) in ``extra``; ``--trace-out PATH`` additionally
dumps each config's Perfetto/chrome-tracing JSONL with a config tag
spliced into the filename.
"""

import contextlib
import functools
import glob
import importlib
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

# Test-rig platform override BEFORE any device use; driver runs leave
# the env unset and land on the attached TPU.
from apex_tpu.utils.platform import apply_test_platform_override
apply_test_platform_override()

import jax.numpy as jnp

BERT_LARGE_PARAMS = 336e6  # ≈ param count incl. embeddings


def _recorded_values(metric):
    """All recorded values for `metric` from driver BENCH_r*.json files,
    oldest first, one value per round. The driver nests only the LAST
    printed line under "parsed" but keeps the full stdout tail under
    "tail" — parse both, or every metric except the tail one loses its
    history (r4's vs_baseline was null for all but one metric)."""
    vals = []
    runs = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json")))
    for path in runs:
        try:
            rec = json.load(open(path))
        except Exception:
            continue
        parsed = rec.get("parsed") or {}
        candidates = [parsed] if isinstance(parsed, dict) else list(parsed)
        for ln in (rec.get("tail") or "").splitlines():
            if ln.startswith("{"):
                try:
                    candidates.append(json.loads(ln))
                except ValueError:
                    pass
        run_val = None  # last occurrence in this round wins
        for c in candidates:
            if isinstance(c, dict) and c.get("metric") == metric \
                    and c.get("value") is not None:
                run_val = c["value"]
        if run_val is not None:
            vals.append(run_val)
    return vals


def emit(metric, value, unit, extra=None, higher_is_better=True):
    """vs_baseline compares to the LATEST recorded round; vs_best to the
    best round EVER, so a regression-after-a-regression can't report >1
    (round-3 verdict weak #8). Both >1 = this run is better."""
    # drop zeros (a recorded 0 would be a zero denominator below) and
    # skip history entirely off-TPU: recorded values are TPU-scale, and
    # CPU smoke runs share metric names at tiny shapes — the ratios
    # would be bogus
    from apex_tpu.utils.platform import has_tpu
    prior = [v for v in _recorded_values(metric) if v] if has_tpu() \
        else []
    rec = {"metric": metric, "value": round(value, 2), "unit": unit,
           "vs_baseline": None}
    flag = None
    if prior:
        prev = prior[-1]
        best = max(prior) if higher_is_better else min(prior)
        ratio = (lambda new, old: new / old) if higher_is_better \
            else (lambda new, old: old / new)
        rec["vs_baseline"] = round(ratio(value, prev), 3)
        rec["vs_best"] = round(ratio(value, best), 3)
        # sustained-regression tripwire: >10% off the best round for TWO
        # consecutive driver rounds (this one AND the last recorded one)
        # is a real regression, not relay noise — emit a dedicated flag
        # line so the driver/reader can't miss it in the JSON stream
        prev_vs_best = round(ratio(prev, best), 3)
        if rec["vs_best"] < 0.9 and prev_vs_best < 0.9:
            flag = {"metric": metric,
                    "flag": "vs_best_below_0.9_two_rounds",
                    "vs_best": rec["vs_best"],
                    "prev_vs_best": prev_vs_best}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    if flag:
        print(json.dumps(flag), flush=True)


def timed(body, init_state, fetch, M, K=4, donate=False, chain=True):
    """Median seconds per iteration of ``body`` (state -> state, a pytree
    step function), measured by DIFFERENCING two scan-chunk lengths.

    The axon relay imposes a ~100 ms fixed cost on every dispatch+fetch
    cycle regardless of the work inside (measured: 50 fused multiplies of
    a 16 MB array and a single one both take ~100 ms end to end), and
    ``block_until_ready`` is not a reliable sync, so: jit ONE M-step
    ``lax.scan`` chunk, run it 1x and 5x (chained, async dispatch), end
    each measurement in a ``float()`` fetch of a chunk-dependent scalar,
    and report (t(5 calls) - t(1 call)) / 4M — the fixed overhead
    cancels exactly. Sanity anchor: the two-program ancestor of this
    methodology reproduces the v5e bf16 peak (197 TFLOP/s) on a 4096^3
    matmul chain, and this variant matches it on the Adam bench.

    ``donate=True`` changes the state protocol: ``init_state`` must be a
    ZERO-ARG FACTORY, each chunk donates its input, and the state
    threads forward across chunks instead of replaying from init. The
    train state then lives ONCE in HBM — the training-realistic
    footprint (real steps donate their buffers). The replay protocol
    keeps init + output alive simultaneously, which is what turned
    BENCH_r04's b16 GPT configs into spurious ResourceExhausted. Timing
    is value-independent on TPU, so an evolving state measures the same
    program the replay did."""
    def chunk_fn(length):
        def chunk_body(state):
            def f(s, _):
                return body(s), ()
            s, _ = jax.lax.scan(f, state, None, length=length)
            return s
        return jax.jit(chunk_body, donate_argnums=0) if donate \
            else jax.jit(chunk_body)

    chunk = chunk_fn(M)
    box = [init_state() if donate else init_state]

    def run(c, ncalls=1):
        """ncalls dispatches of program ``c`` (async, back-to-back on
        device), box-threaded under donation, one fetch at the end."""
        state = c(box[0])
        for _ in range(ncalls - 1):
            state = c(state)
        if donate:
            box[0] = state
        float(fetch(state))

    def t_of(c, ncalls=1):
        run(c, ncalls)  # compile + warm
        ts = []
        for _ in range(K):
            t0 = time.perf_counter()
            run(c, ncalls)
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    if chain:
        # ONE compiled program: the long measurement is 5 CHAINED
        # dispatches of the same jitted scan, not a separately-compiled
        # 5M-scan. jit dispatch is async, so the chain runs back-to-back
        # on device and the fetch syncs once at the end;
        # (t(5 calls) - t(1 call)) / 4M cancels the relay's fixed
        # dispatch+fetch cost exactly like the two-program scheme —
        # validated on the Adam bench (12.56 ms vs the two-program
        # 11.9-12.6 ms band) — while paying ONE XLA compile. That
        # matters: the scan-of-50 FusedAdam chunk alone took ~390 s to
        # compile through the relay, which is what pushed opt_adam past
        # its config cap in the r5 shakeout run.
        return max(t_of(chunk, 5) - t_of(chunk, 1), 1e-9) / (4 * M)

    # chain=False: the two-PROGRAM differencing ancestor — scan(M) and
    # scan(5M) each dispatched once, (t2-t1)/4M. Needed when the state
    # is a MANY-LEAF pytree: a chained dispatch pays host-side pytree
    # flattening per call (~38 ms for the 1024-small-tensor Adam state),
    # and the chain scheme puts 4 extra dispatches inside the measured
    # delta — dispatch/M lands in the per-iter number (measured: the
    # tree-path small-tensor metric read 2.75 ms vs its true ~0.9 ms).
    # Two programs pay double compile, so chain=False is only for
    # benches whose chunk compiles fast.
    c2 = chunk_fn(5 * M)
    return max(t_of(c2) - t_of(chunk), 1e-9) / (4 * M)


def checked(metric, unit_scale, body, init_state, fetch, M, K=4,
            donate=False, chain=True):
    """``timed`` plus a sanity gate against the metric's own driver
    history: if the fresh measurement lands >3x off the best
    driver-recorded value, measure ONCE more. The two directions are NOT
    symmetric: relay/allocator damage only ever ADDS time (BENCH_r04:
    flash seq2048 read 27x slow while seq4096 in the same process was
    healthy), so a too-SLOW outlier keeps min(). A too-FAST reading has
    no such mechanism — min() would enshrine exactly the broken-chain /
    dead-fetch readings this gate exists to catch — so it keeps the
    re-measure when that lands back inside the plausible band, else the
    SLOWER of the two, and the line is marked suspect either way.
    Returns (dt_seconds, extra) where extra carries the retry
    provenance for the emitted line."""
    dt = timed(body, init_state, fetch, M, K, donate=donate, chain=chain)
    extra = {}
    from apex_tpu.utils.platform import has_tpu
    # the recorded history is TPU-scale; gating CPU smoke runs against
    # it would force a meaningless retry of every metric
    prior = [v for v in _recorded_values(metric) if v] if has_tpu() \
        else []
    if prior:
        # gate against the BEST prior round: a damaged recorded value
        # (r4's 94.99 ms flash seq2048) must not poison the gate the
        # way gating on the latest round would
        best = min(prior)
        ratio = dt * unit_scale / best
        if ratio > 3.0 or ratio < 1.0 / 3.0:
            first = dt
            second = timed(body, init_state, fetch, M, K,
                           donate=donate, chain=chain)
            if ratio > 3.0:
                dt = min(first, second)
            elif second * unit_scale / best >= 1.0 / 3.0:
                dt = second  # re-measure is history-consistent: trust it
            else:
                dt = max(first, second)
            final = dt * unit_scale / best
            extra = {"retried": True,
                     "first": round(first * unit_scale, 2),
                     "suspect": not (1.0 / 3.0 <= final <= 3.0)}
    return dt, extra


# Driver mode runs ONE measured-winner config per model bench; sweeps
# (batch x remat) burned BENCH_r04's budget into rc=124 and two OOMs.
# Set BENCH_SWEEP=1 to re-tune candidates at build time.
_SWEEP = os.environ.get("BENCH_SWEEP") == "1"


# -- config 2: LN microbench ------------------------------------------------

def bench_layer_norm(on_tpu):
    from apex_tpu.normalization import fused_layer_norm_affine

    rows = 8192 if on_tpu else 64
    for h in (1024, 4096):
        x = jax.random.normal(jax.random.PRNGKey(0), (rows, h), jnp.bfloat16)
        # |w| < 1 makes the dy -> dx chain strictly contracting (LN's
        # input-grad is a projection scaled by w·rstd), so the raw dx
        # can feed the next iteration's dy with NO normalization pass:
        # the body moves exactly the 5 streams the GB/s model counts.
        # Values decay toward zero; TPU arithmetic is value-independent,
        # so timing is unaffected and the chain stays data-dependent.
        w = jnp.full((h,), 0.9, jnp.float32)
        b = jnp.zeros((h,), jnp.float32)
        dy0 = jax.random.normal(jax.random.PRNGKey(1), (rows, h),
                                jnp.bfloat16)

        def body(dy, h=h):
            # Training-shaped workload (changed r4): fwd + bwd with an
            # EXTERNAL cotangent dy, as an upstream layer supplies.
            # Rounds 1-3 measured grad(sum(LN(x)^2)) — a self-cotangent
            # body whose dy = 2y fuses away; numbers are not comparable
            # across that change.
            return jax.grad(
                lambda x: jnp.sum(
                    fused_layer_norm_affine(x, w, b, h, 1e-5).astype(
                        jnp.float32) * dy.astype(jnp.float32)))(x)

        # M sized so the 4M-iteration delta is far above the axon
        # relay's ~±20 ms dispatch noise
        name = f"fused_layer_norm_fwdbwd_h{h}"
        dt, extra = checked(name, 1e6, body, dy0,
                            lambda s: jnp.sum(s.astype(jnp.float32)),
                            M=400 if on_tpu else 2)
        # bytes: read x (fwd) + read x,dy (bwd) + write y, dx ~ 5 * 2B
        gbps = 5 * rows * h * 2 / dt / 1e9
        extra.update({"rows": rows, "GBps": round(gbps, 1)})
        emit(name, dt * 1e6, "us/iter", extra=extra,
             higher_is_better=False)


# -- config 3: optimizer step on BERT-Large param set -----------------------

def _make_optimizer(which):
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    return {
        "adam": lambda: FusedAdam(lr=1e-4, weight_decay=0.01),
        "lamb": lambda: FusedLAMB(lr=1e-3, weight_decay=0.01),
    }[which]()


def bench_one_optimizer(which, on_tpu):
    """One optimizer per subprocess: BERT-Large fp32 state doesn't fit
    twice in HBM (measured ResourceExhausted when chained in-process),
    and the donating timer keeps exactly one copy live."""
    from apex_tpu.models import bert_large, bert_tiny, init_bert

    cfg = bert_large() if on_tpu else bert_tiny()
    # grads from shape metadata only — no second on-device init
    shapes = jax.eval_shape(
        lambda: init_bert(jax.random.PRNGKey(0), cfg))
    grads = jax.tree.map(lambda sd: jnp.full(sd.shape, 1e-4, sd.dtype),
                         shapes)
    opt = _make_optimizer(which)

    def make_init():
        params = init_bert(jax.random.PRNGKey(0), cfg)
        return params, opt.init(params)

    def body(state):
        p, s = state
        return opt.step(grads, p, s)

    name = f"fused_{which}_step_bert_large_params"
    dt, extra = checked(name, 1e3, body, make_init,
                        lambda s: jnp.sum(s[0]["pooler"]["bias"]),
                        M=10 if on_tpu else 2, donate=True)
    emit(name, dt * 1e3, "ms/step", extra=extra, higher_is_better=False)


def bench_flat_vs_tree_many_tensors(on_tpu):
    """The flat path's actual claim (fused_adam docstring): it pays off
    when per-leaf overhead dominates — a 1024-small-tensor param set
    (the BERT-Large set is 400 LARGE tensors, where the tree path's XLA
    fusion already wins and the flat round-trip can't fit in HBM)."""
    from apex_tpu.optimizers import FusedAdam

    n = 1024 if on_tpu else 32
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    params = {f"t{i}": jax.random.normal(k, (64, 128)) for i, k in
              enumerate(keys)}
    grads = jax.tree.map(lambda p: jnp.full_like(p, 1e-4), params)
    for name, opt in (
            ("tree", FusedAdam(lr=1e-4, weight_decay=0.01)),
            ("flat", FusedAdam(lr=1e-4, weight_decay=0.01,
                               use_flat_kernel=True))):
        opt_state = opt.init(params)

        def body(state, opt=opt):
            p, s = state
            return opt.step(grads, p, s)

        metric = f"fused_adam_{name}_{n}_small_tensors"
        # chain=False: a 1024-leaf state pays ~38 ms of host pytree
        # flattening per dispatch — the chain scheme's 4 extra
        # dispatches would land dispatch/M in the metric (see timed)
        dt, extra = checked(metric, 1e3, body, (params, opt_state),
                            lambda s: jnp.sum(s[0]["t0"]),
                            M=20 if on_tpu else 2, chain=False)
        emit(metric, dt * 1e3, "ms/step", extra=extra,
             higher_is_better=False)


# -- shared BERT train-step builder ----------------------------------------

def _bert_step(batch, seq, cfg, m_dtype=jnp.float32, emit_compute=False):
    """Returns (train_step, make_state, (ids, mask)); ``make_state`` is
    a zero-arg factory so the donating timer holds ONE state copy.

    ``m_dtype``/``emit_compute`` are the reduced-precision state levers:
    bf16 Adam first moment, and the fused bf16 cast-out carried in the
    loop state and consumed via ``cast_model(precast=...)`` — the O2
    per-step fp32->bf16 master re-cast disappears. With ``emit_compute``
    the state/step grow a 4th ``compute`` slot."""
    from apex_tpu import amp
    from apex_tpu.models import apply_bert, init_bert, mlm_loss
    from apex_tpu.optimizers import FusedAdam

    h = amp.initialize(opt_level="O2", loss_scale="dynamic")
    opt = FusedAdam(lr=1e-4, weight_decay=0.01, m_dtype=m_dtype,
                    emit_compute_params=emit_compute)

    def make_state():
        params = init_bert(jax.random.PRNGKey(0), cfg)
        base = (params, opt.init(params), h.init_state())
        if not emit_compute:
            return base
        # copy: outside jit the keep-fp32 norm leaves of cast_model come
        # back as the SAME arrays as params — the donating timer would
        # see one buffer donated twice
        compute = jax.tree.map(jnp.copy, h.cast_model(params))
        return base + (compute,)

    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    mask = jnp.ones((batch, seq), jnp.int32)

    def train_step(master, opt_state, scaler_state, *rest):
        *compute, ids, mask = rest

        def loss_fn(p):
            out = apply_bert(p, cfg, ids, mask)
            return mlm_loss(out["mlm_logits"], ids, mask)

        p = h.cast_model(master, precast=compute[0] if compute else None)
        loss, grads, found_inf, scaler_state = h.value_and_grad(loss_fn)(
            p, scaler_state)
        if emit_compute:
            master, opt_state, c = opt.step(
                grads, master, opt_state, found_inf=found_inf,
                compute_params=p)
            return master, opt_state, scaler_state, c, loss
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        return master, opt_state, scaler_state, loss

    return train_step, make_state, (ids, mask)


# -- config 4: DDP BERT over all local devices ------------------------------

def bench_ddp_bert(on_tpu):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from apex_tpu.models import bert_large, bert_tiny

    n = jax.device_count()
    cfg = bert_large() if on_tpu else bert_tiny()
    # b=64/chip: the measured headline winner under the donating timer
    # (see bench_headline's sweep record)
    per_dev_batch, seq = (64, 128) if on_tpu else (2, 64)
    batch = per_dev_batch * n
    mesh = Mesh(jax.devices(), ("data",))
    train_step, make_state, (ids, mask) = _bert_step(batch, seq, cfg)
    # GSPMD DP: batch sharded over the data axis, params replicated —
    # jit propagates the sharding; XLA inserts the grad all-reduce.
    data_sharding = NamedSharding(mesh, P("data", None))
    ids = jax.device_put(ids, data_sharding)
    mask = jax.device_put(mask, data_sharding)

    def body(st):
        m, o, sc, _ = train_step(st[0], st[1], st[2], ids, mask)
        return (m, o, sc, _)

    dt = timed(body, lambda: (*make_state(), jnp.float32(0)),
               lambda s: s[3], M=10 if on_tpu else 2, donate=True)
    sps = batch / dt / n
    emit(f"bert_ddp_dp{n}_step", sps, "samples/sec/chip",
         extra={"per_device_batch": per_dev_batch, "devices": n,
                "step_ms": round(dt * 1e3, 2)})


# -- config 5 (from round 3): TP GPT ---------------------------------------

def bench_tp_gpt(on_tpu):
    try:
        from apex_tpu.models.gpt import gpt_tp_bench
    except ImportError:
        return  # GPT lands later this round
    n = jax.device_count()
    # b=8 + full per-layer remat is the measured winner. r5 swept the
    # whole surface: b8/b12/b16 x {full remat, dots_saveable selective
    # remat} all land in 28.8-30.1 samples/s (per-SAMPLE cost rises
    # with batch), TRUE no-remat crashes the relay's compile helper at
    # b>=8, and selective remat performs identically to full remat —
    # the step is not recompute-dominated (see BASELINE.md GPT
    # roofline). The sweep only runs at build time under BENCH_SWEEP=1.
    if not on_tpu:
        configs = [(None, False)]
    elif _SWEEP:
        configs = [(8, True), (8, "dots_saveable"), (12, "dots_saveable"),
                   (16, "dots_saveable")]
    else:
        configs = [(8, True)]
    best = None
    body = make_init = fetch = None
    for batch, remat in configs:
        # drop the previous config's closures BEFORE building the next;
        # the donating timer already keeps only one live train state
        body = make_init = fetch = None
        try:
            body, make_init, fetch, b = gpt_tp_bench(on_tpu, n,
                                                     batch=batch,
                                                     remat=remat)
            dt = timed(body, make_init, fetch, M=5 if on_tpu else 2,
                       donate=True)
        except Exception as e:
            print(json.dumps({"metric": f"gpt_b{batch}_remat{remat}",
                              "error": repr(e)[:160]}), flush=True)
            continue
        if _SWEEP:
            print(json.dumps({"metric": f"gpt_b{batch}_remat{remat}",
                              "sweep_samples_per_sec": round(b / dt, 2),
                              "step_ms": round(dt * 1e3, 2)}), flush=True)
        if best is None or b / dt > best[0]:
            best = (b / dt, b, remat, dt)
    if best is None:
        raise RuntimeError("every GPT bench config failed (see above)")
    sps, b, remat, dt = best
    emit(f"gpt_tp{n}_step", sps, "samples/sec",
         extra={"devices": n, "batch": b, "remat": remat,
                "step_ms": round(dt * 1e3, 2)})


# -- serving: batched KV-cached decode --------------------------------------

def _decode_bench_setup(on_tpu, cache_dtype, slots=None):
    """(body, make_init, fetch, slots, s_max, cfg): one greedy decode step
    over the serving KV cache for every slot — the steady-state
    continuous-batching inner loop, no host scheduler in the timed
    region. Lengths park mid-cache and reset before reaching the end so
    a scan chunk of any length measures the same in-range program."""
    import dataclasses

    from apex_tpu.models.gpt import GPTConfig, gpt_tiny, init_gpt
    from apex_tpu.serving.cache import init_cache
    from apex_tpu.serving.decode import (
        _decode_core, _dense, _embed_unsharded, _logits_unsharded,
    )

    if on_tpu:
        # gpt_medium-class decode on one chip; bf16 params (inference)
        cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        ffn_hidden_size=4096, vocab_size=50304,
                        max_position_embeddings=1024, use_rope=True,
                        hidden_dropout=0.0)
        slots = 32 if slots is None else slots
        s_max = 1024
    else:
        cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                                  hidden_dropout=0.0)
        slots = 4 if slots is None else slots
        s_max = 64
    embed = _embed_unsharded(cfg, None)
    active = jnp.ones((slots,), bool)

    def make_init():
        params = init_gpt(jax.random.PRNGKey(0), cfg,
                          jnp.bfloat16 if on_tpu else jnp.float32)
        cache = init_cache(cfg, slots, s_max, cache_dtype)
        cache = cache._replace(
            lengths=jnp.full((slots,), s_max // 2, jnp.int32))
        return params, cache, jnp.zeros((slots,), jnp.int32)

    def body(state):
        params, cache, tokens = state
        cache = cache._replace(lengths=jnp.where(
            cache.lengths >= s_max - 1, jnp.int32(s_max // 2),
            cache.lengths))
        cache, logits = _decode_core(
            params, cfg, cache, tokens, active, embed_fn=embed,
            dense_fns=(_dense,) * 4, logits_fn=_logits_unsharded)
        return params, cache, jnp.argmax(logits, -1).astype(jnp.int32)

    fetch = lambda s: (jnp.sum(s[1].lengths)  # noqa: E731
                       + jnp.sum(s[2])).astype(jnp.float32)
    return body, make_init, fetch, slots, s_max, cfg


def _decode_cost_numbers(cfg, slots, depth, param_dtype, cache_dtype,
                         quantized=False):
    """(model_bytes_per_token, kv_bytes_per_step, weight_bytes_per_token)
    from the APX6xx abstract cost interpreter, over the same decode
    program at the parked cache depth. Pure trace — no compile, no
    device work — so it prices the roofline the measured tokens/sec
    should be compared against. ``kv_bytes_per_step`` isolates the cache
    slice of that traffic: the full K/V read (both cache invars, charged
    once per step by the interpreter) plus the in-place row writes
    (``delta_write_bytes``) — exactly the term the paged layout makes
    length-proportional (see the ``decode_paged_vs_dense`` A/B pair and
    BASELINE r10). ``weight_bytes_per_token`` isolates the parameter
    slice of the interpreter's invar read charge, amortized over the
    batch — the term weight-only int8 halves (``quantized=True`` prices
    the int8 tree: same program, int8 kernel invars + fp32 scales)."""
    import math

    from apex_tpu.lint.traced import cost
    from apex_tpu.models.gpt import init_gpt
    from apex_tpu.serving.cache import init_cache
    from apex_tpu.serving.decode import make_decode_fn

    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg, param_dtype), jax.random.PRNGKey(0))
    if quantized:
        from apex_tpu.quant.params import quantize_params

        params = quantize_params(params)
    cache = jax.eval_shape(
        functools.partial(init_cache, cfg, slots, depth, cache_dtype))
    closed = jax.make_jaxpr(make_decode_fn(cfg, quantized=quantized))(
        params, cache, jax.ShapeDtypeStruct((slots,), jnp.int32),
        jax.ShapeDtypeStruct((slots,), jnp.bool_))
    rep = cost.compute(closed, __file__, "gpt_decode")
    kv_read = sum(math.prod(t.shape) * t.dtype.itemsize
                  for t in (cache.k, cache.v))
    weight_read = sum(math.prod(t.shape) * t.dtype.itemsize
                      for t in jax.tree_util.tree_leaves(params))
    return (int(rep.hbm_total_bytes // slots),
            int(kv_read + rep.delta_write_bytes),
            int(weight_read // slots))


# `--trace-out PATH` (any position on the CLI) makes the serving
# configs dump their tracer's Perfetto/chrome-tracing JSONL; each dump
# splices a config tag in before the extension so one flag serves the
# whole run. None = tracing stays on (the registry feeds the latency
# percentiles either way) but nothing is written.
_TRACE_OUT = None


def _maybe_dump_trace(tracer, tag):
    if not _TRACE_OUT or tracer is None or not tracer.enabled:
        return
    root, ext = os.path.splitext(_TRACE_OUT)
    tracer.dump_jsonl(f"{root}.{tag}{ext or '.jsonl'}")


def _serving_stats_probe():
    """Non-zero ``ServingStats`` counters from a tiny scheduler run
    under a pinned fault schedule (pool pressure + one injected fault
    per site class). Deterministic — the same schedule every round —
    so the driver tracks the degradation MACHINERY (counters move, run
    completes typed) rather than a flaky fault lottery."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  FaultInjector, PagedDecodeEngine,
                                  Request)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    inj = FaultInjector(schedule={"prefill_exec": (0,),
                                  "decode_exec": (0,)})
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=32,
                            num_pages=8, page_size=4, buckets=(16, 32),
                            injector=inj)
    sched = ContinuousBatchingScheduler(eng, eos_id=-1, audit=True)
    for i in range(3):
        sched.submit(Request(prompt=(7 + i, 11, 13, 17, 19),
                             max_new_tokens=4))
    sched.run()
    assert all(o.reason for o in sched.outcomes.values())
    return {k: v for k, v in sched.stats.as_dict().items() if v}


def _observed_decode_probe():
    """Registry-derived tick-clock latency percentiles (TTFT and
    inter-token gaps, in ticks) from a tiny traced scheduler drain —
    more submissions than slots, so the queue wait shows up in TTFT.
    Deterministic: the tick clock is replay-exact, so these numbers
    move only when scheduling behavior moves, never with host noise."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  PagedDecodeEngine, Request, Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    trc = Tracer()
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=32,
                            num_pages=20, page_size=4, buckets=(16, 32),
                            tracer=trc)
    sched = ContinuousBatchingScheduler(eng, eos_id=-1)
    for i in range(4):
        sched.submit(Request(prompt=(7 + i, 11, 13), max_new_tokens=8))
    sched.run()
    _maybe_dump_trace(trc, "decode")
    return trc.latency_summary()


_SCENARIO_SEED = {"chat": 101, "batch_completion": 102,
                  "long_context": 103, "shared_prefix": 104,
                  "cache_hierarchy": 105, "multitenant": 106}


def _scenario_arrivals(name, vocab):
    """Seeded-Poisson arrival schedule for one workload mix: a list of
    ``(tick, Request)`` sorted by arrival tick. Inter-arrival gaps are
    Poisson draws from a fixed ``numpy`` generator (seeds in
    ``_SCENARIO_SEED``, one per mix — documented in benchmarking.rst),
    so every run replays the identical workload: chat (short prompts,
    steady trickle), batch-completion (one burst at t=0), long-context
    (40-56-token prompts landing amid short chats — the head-of-line
    case chunked prefill exists for) and shared-prefix (a common
    16-token, page-aligned prefix the paged engine's prefix cache can
    serve)."""
    import numpy as np
    from apex_tpu.serving import Request

    rng = np.random.default_rng(_SCENARIO_SEED[name])

    def tok(n):
        return tuple(int(t) for t in rng.integers(0, vocab, n))

    # arrival ticks are on the scheduler's WORK-CHARGED clock (one
    # tick ~ one token of sequential depth), so gap means are sized
    # against per-request service time (prompt + new tokens), not
    # against host steps
    out, t = [], 0
    if name == "chat":
        for _ in range(8):
            t += int(rng.poisson(12.0))
            out.append((t, Request(prompt=tok(int(rng.integers(4, 13))),
                                   max_new_tokens=int(
                                       rng.integers(4, 9)))))
    elif name == "batch_completion":
        for _ in range(6):
            out.append((0, Request(prompt=tok(int(rng.integers(8, 17))),
                                   max_new_tokens=8)))
    elif name == "long_context":
        for j in range(6):
            t += int(rng.poisson(16.0))
            n = int(rng.integers(40, 57)) if j % 3 == 1 \
                else int(rng.integers(4, 9))
            out.append((t, Request(prompt=tok(n), max_new_tokens=4)))
    elif name == "shared_prefix":
        prefix = tok(16)
        for _ in range(8):
            t += int(rng.poisson(6.0))
            out.append((t, Request(
                prompt=prefix + tok(int(rng.integers(2, 7))),
                max_new_tokens=4)))
    elif name == "cache_hierarchy":
        # zipf-popular 12-token "system prompts" over a pool too small
        # to keep them all HBM-resident: hot prefixes churn out, spill
        # to the host tier, and promote back on re-arrival — the
        # hierarchical KV-cache's home workload
        bases = [tok(12) for _ in range(4)]
        for _ in range(10):
            t += int(rng.poisson(8.0))
            r = min(int(rng.zipf(2.0)), len(bases)) - 1
            out.append((t, Request(
                prompt=bases[r] + tok(int(rng.integers(2, 7))),
                max_new_tokens=4)))
    elif name == "multitenant":
        # the adversarial three-class tenancy mix: a NOISY NEIGHBOR
        # burst-submitting long prompts with long decodes at t=0, a
        # batch tenant piling on at t=0, and an interactive chat
        # trickle arriving while both floods drain — the workload the
        # weighted-fair-share + priority front-end exists to protect
        for _ in range(4):
            out.append((0, Request(prompt=tok(int(rng.integers(24, 33))),
                                   max_new_tokens=12,
                                   tenant_id="noisy")))
        for _ in range(4):
            out.append((0, Request(prompt=tok(int(rng.integers(8, 17))),
                                   max_new_tokens=6,
                                   tenant_id="batch")))
        for _ in range(6):
            t += int(rng.poisson(10.0))
            out.append((t, Request(prompt=tok(int(rng.integers(3, 7))),
                                   max_new_tokens=4,
                                   tenant_id="interactive")))
        out.sort(key=lambda e: e[0])  # stable: FIFO within a tick
    else:
        raise ValueError(f"unknown scenario {name!r}")
    return out


def _drive_poisson(sched, arrivals):
    """Interleave the arrival schedule with public ``step()`` ticks —
    the open-loop load generator the scheduler's instance-held
    watchdog state exists for. Arrivals are paced against the
    scheduler's work-charged ``clock`` (decode-step equivalents, the
    wall-time proxy) and submitted with ``at_tick=`` backdating, so a
    request that "arrives" while a charged forward is in flight still
    measures the wait it spent behind that forward. Returns the
    committed streams in submission order."""
    i = 0
    while i < len(arrivals) or sched.busy:
        while i < len(arrivals) and arrivals[i][0] <= sched.clock:
            t, req = arrivals[i]
            sched.submit(req, at_tick=t)
            i += 1
        if sched.busy:
            sched.step()
        elif i < len(arrivals):
            sched.advance_clock(arrivals[i][0])
    return [list(sched.outcomes[rid].tokens)
            for rid in sorted(sched.outcomes)]


def bench_gpt_serving_scenarios(on_tpu):
    """Driver config ``gpt_serving_scenarios``: the seeded-Poisson
    workload mixes replayed through the chunked-prefill scheduler, one
    line per mix with registry-derived p50/p95/p99 TTFT and ITL in
    scheduler ticks. The tick clock charges every forward its
    sequential depth (decode-step equivalents), so these percentiles
    move only when scheduling POLICY moves — host noise and relay
    drift cannot touch them. This config tracks the p99-ITL bound the
    chunked scheduler exists to hold."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  PagedDecodeEngine, PrefixRegistry,
                                  Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    names = ("chat", "batch_completion", "long_context",
             "shared_prefix", "cache_hierarchy")
    # APEX_BENCH_SCENARIOS=chat[,mix...] narrows the sweep — the
    # run_tests.sh quick tier smokes a single mix this way
    only = os.environ.get("APEX_BENCH_SCENARIOS")
    if only:
        names = tuple(n for n in names if n in only.split(","))
    for name in names:
        metric = f"gpt_serving_{name}_itl_p99_ticks"
        try:
            trc = Tracer()
            # fresh engine per mix: the latency histograms live on the
            # tracer's registry and must not bleed across scenarios.
            # The cache_hierarchy mix runs over a DELIBERATELY small
            # pool plus a host tier, so its hot prefixes spill and
            # promote instead of staying HBM-resident
            tier = PrefixRegistry(1 << 20) \
                if name == "cache_hierarchy" else None
            eng = PagedDecodeEngine(
                params, cfg, num_slots=2, max_len=64,
                num_pages=20 if tier is not None else 48,
                page_size=4, buckets=(16, 64), tracer=trc,
                host_tier=tier)
            sched = ContinuousBatchingScheduler(eng, eos_id=-1,
                                                chunk_tokens=8)
            arrivals = _scenario_arrivals(name, cfg.vocab_size)
            streams = _drive_poisson(sched, arrivals)
            lat = trc.latency_summary()
            extra = {"seed": _SCENARIO_SEED[name],
                     "requests": len(arrivals),
                     "tokens": sum(len(s) for s in streams),
                     "prefill_chunks": sched.stats.prefill_chunks,
                     "chunk_tokens": 8,
                     "tick_token_budget": sched.tick_token_budget}
            if tier is not None:
                extra.update(
                    host_spills=eng.stats.host_spills,
                    host_promotes=eng.stats.host_promotes,
                    host_promote_ticks=eng.stats.host_promote_ticks,
                    **{k: (round(v, 4) if isinstance(v, float) else v)
                       for k, v in tier.stats().items()})
            extra.update(lat)
            _maybe_dump_trace(trc, f"scenario_{name}")
            emit(metric, lat.get("itl_p99", 0.0), "ticks", extra=extra,
                 higher_is_better=False)
        except Exception as e:  # one mix must never sink the others
            print(json.dumps({"metric": metric,
                              "error": repr(e)[:200]}), flush=True)


def bench_gpt_serving_pool(on_tpu):
    """Driver config ``serving_pool_scaling``: the long_context
    seeded-Poisson mix replayed through replica pools of growing
    shape — 1x1, 2x1, 2x2 prefill x decode — one line per shape with
    GOODPUT (committed tokens per scheduler tick) plus registry-derived
    TTFT/ITL percentiles. The pool's link-overlap clock charges each
    admission pass only the reshard horizon it EXTENDS, so a second
    prefill replica absorbs concurrent handoffs for free and goodput
    must be monotonically non-decreasing up the sweep — asserted, not
    just reported, right after the committed streams are asserted
    bit-identical across every shape (scale may only move the clock)."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (FaultInjector, PagedDecodeEngine,
                                  PoolRouter, Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)

    def engine(trc, inj):
        return PagedDecodeEngine(params, cfg, num_slots=2, max_len=64,
                                 num_pages=48, page_size=4,
                                 buckets=(16, 64), tracer=trc,
                                 injector=inj)

    results = []                       # (shape, streams, goodput)
    for n_prefill, n_decode in ((1, 1), (2, 1), (2, 2)):
        shape = f"{n_prefill}x{n_decode}"
        metric = f"gpt_serving_pool_{shape}_goodput"
        try:
            trc = Tracer()
            inj = FaultInjector()      # one injector, shared — inert
            sched = PoolRouter(
                [engine(trc, inj) for _ in range(n_prefill)],
                [engine(trc, inj) for _ in range(n_decode)],
                eos_id=-1)
            arrivals = _scenario_arrivals("long_context",
                                          cfg.vocab_size)
            streams = _drive_poisson(sched, arrivals)
            tokens = sum(len(s) for s in streams)
            goodput = tokens / max(1, sched.clock)
            lat = trc.latency_summary()
            if results:
                assert streams == results[0][1], \
                    f"pool {shape} streams diverged from 1x1"
                assert goodput >= results[-1][2] - 1e-12, \
                    (f"goodput regressed {results[-1][0]} -> {shape}: "
                     f"{results[-1][2]:.4f} -> {goodput:.4f}")
            results.append((shape, streams, goodput))
            extra = {"seed": _SCENARIO_SEED["long_context"],
                     "requests": len(arrivals), "tokens": tokens,
                     "clock_ticks": sched.clock,
                     "reshards": sched.stats.reshards,
                     "transfers": sched.stats.transfers,
                     "remote_prefills": sched.stats.remote_prefills}
            extra.update(lat)
            _maybe_dump_trace(trc, f"pool_{shape}")
            emit(metric, round(goodput, 4), "tokens/tick", extra=extra,
                 higher_is_better=True)
        except Exception as e:  # one shape must never sink the others
            print(json.dumps({"metric": metric,
                              "error": repr(e)[:200]}), flush=True)


def _run_multitenant(params, cfg, tenanted, only=None):
    """One replay of the ``multitenant`` adversarial mix. Returns
    ``(streams, gaps, stalls, tracer, sched)`` where both latency maps
    are tenant -> per-token scheduler-tick samples measured at the
    STREAMING SINK (the consumer's view), so the tenanted and
    untenanted sides are scored by the identical host-side ruler —
    the untenanted scheduler has no tenant-labeled histograms, but its
    StreamMux still carries the request's tenant tag. ``gaps`` is the
    decode-phase inter-token gap (first token excluded — classic ITL);
    ``stalls`` additionally counts the FIRST token's wait since
    arrival, because an untenanted FIFO hides ALL of its queueing pain
    in TTFT and a pure-ITL ruler would score the starvation as a
    win."""
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  PagedDecodeEngine, StreamMux, Tenant,
                                  TenancyPolicy, Tracer)

    trc = Tracer()
    eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=64,
                            num_pages=48, page_size=4, buckets=(16, 64),
                            tracer=trc)
    gaps, stalls, last = {}, {}, {}
    arrival_tick = {}
    sched = None

    def sink(rid, tenant, toks):
        tick = sched.clock
        prev = last.get(rid)
        if prev is not None:
            # the batch's first token carries the inter-batch gap, the
            # rest landed the same tick (speculative burst) — the same
            # accounting the scheduler's ITL histograms use
            gaps.setdefault(tenant, []).append(tick - prev)
            gaps[tenant].extend([0] * (len(toks) - 1))
            stalls.setdefault(tenant, []).append(tick - prev)
            stalls[tenant].extend([0] * (len(toks) - 1))
        else:
            stalls.setdefault(tenant, []).append(
                tick - arrival_tick[rid])
            stalls[tenant].extend([0] * (len(toks) - 1))
        last[rid] = tick

    pol = None
    if tenanted:
        # interactive gets 4x weight AND the priority rung (may
        # preempt a resident flood slot); batch outranks noisy on
        # weight alone — the declared protection ladder
        pol = TenancyPolicy((Tenant("interactive", weight=4.0,
                                    priority=1, itl_slo_ticks=8),
                             Tenant("noisy", weight=1.0),
                             Tenant("batch", weight=2.0)))
    mux = StreamMux(injector=eng.injector, tracer=trc, stats=eng.stats,
                    sink=sink)
    # chunked prefill on BOTH sides: the flood's 24-32-token prompts
    # would otherwise open prefill-sized gaps in every co-resident
    # stream, swamping the fairness signal with the head-of-line
    # effect the chunked tier already bounds
    sched = ContinuousBatchingScheduler(eng, eos_id=-1, chunk_tokens=8,
                                        tenancy=pol, streams=mux)
    arrivals = _scenario_arrivals("multitenant", cfg.vocab_size)
    if only is not None:
        arrivals = [(t, r) for t, r in arrivals if r.tenant_id in only]
    # request ids are assigned in submission order == arrival order
    arrival_tick.update({i: t for i, (t, _) in enumerate(arrivals)})
    streams = _drive_poisson(sched, arrivals)
    return streams, gaps, stalls, trc, sched


def _gap_p99(gaps, tenant):
    xs = sorted(gaps.get(tenant, ()))
    if not xs:
        return 0.0
    return float(xs[min(len(xs) - 1, int(0.99 * len(xs)))])


def bench_gpt_serving_multitenant(on_tpu):
    """Driver config ``serving_multitenant``: the adversarial
    three-class Poisson mix (noisy-neighbor flood x batch burst x
    interactive trickle) through the tenanted, streaming scheduler.
    The committed streams are asserted BIT-IDENTICAL to the untenanted
    replay before any latency is read — tenancy moves WHEN work runs,
    never WHAT commits — then the line scores the interactive tenant's
    p99 ITL in scheduler ticks with per-tenant summaries, preemption/
    SLO counters and stream-delivery stats alongside."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    metric = "gpt_serving_multitenant_interactive_itl_p99_ticks"
    try:
        streams_t, gaps_t, stalls_t, trc, sched = _run_multitenant(
            params, cfg, tenanted=True)
        streams_u, gaps_u, stalls_u, _, _ = _run_multitenant(
            params, cfg, tenanted=False)
        assert streams_t == streams_u, \
            "tenanted committed streams diverged from untenanted"
        lat = trc.tenant_latency_summary("interactive")
        extra = {"seed": _SCENARIO_SEED["multitenant"],
                 "requests": len(streams_t),
                 "tokens": sum(len(s) for s in streams_t),
                 "interactive_itl_p99_untenanted":
                     _gap_p99(gaps_u, "interactive"),
                 "interactive_stall_p99":
                     _gap_p99(stalls_t, "interactive"),
                 "interactive_stall_p99_untenanted":
                     _gap_p99(stalls_u, "interactive"),
                 "noisy_stall_p99": _gap_p99(stalls_t, "noisy"),
                 "noisy_stall_p99_untenanted":
                     _gap_p99(stalls_u, "noisy"),
                 "chunk_deferrals": sched.stats.chunk_deferrals,
                 "tenant_preemptions": sched.stats.tenant_preemptions,
                 "slo_violations": sched.stats.slo_violations,
                 "stream_batches": sched.stats.stream_batches,
                 "stream_tokens": sched.stats.stream_tokens}
        extra.update(lat)
        _maybe_dump_trace(trc, "multitenant")
        emit(metric, _gap_p99(gaps_t, "interactive"), "ticks",
             extra=extra, higher_is_better=False)
    except Exception as e:
        print(json.dumps({"metric": metric,
                          "error": repr(e)[:200]}), flush=True)


def _tenancy_vs_untenanted_ab_pair(on_tpu):
    """(side_a, side_b): the tenanted scheduler (4x interactive
    weight + priority rung + fair-share chunk throttle) vs untenanted
    FIFO on the same seeded adversarial multitenant mix, scored as the
    INTERACTIVE tenant's P99 PER-TOKEN DELIVERY STALL IN SCHEDULER
    TICKS at the streaming sink — the first token's wait counts from
    ARRIVAL, because FIFO hides all its queueing pain in TTFT and a
    pure inter-token ruler would score the starvation as a win. The
    committed streams are asserted bit-identical FIRST — fairness may
    only move the clock — then the noisy-neighbor contract is pinned:
    the interactive DECODE-PHASE tail (classic ITL, first token
    excluded) stays within 1.5x its solo run (interactive arrivals
    alone on an idle engine) while the noisy tenant's stall tail
    strictly DEGRADES (the flood pays for the protection). Both sides
    replay identical arrivals, so each sample is an exact replica and
    the band collapses to the point ratio. Ratio < 1 = fair share +
    priority protect the interactive tail."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)

    streams_t, gaps_t, stalls_t, _, _ = _run_multitenant(
        params, cfg, True)
    streams_u, gaps_u, stalls_u, _, _ = _run_multitenant(
        params, cfg, False)
    assert streams_t == streams_u, \
        "tenanted committed streams diverged from untenanted"
    _, gaps_solo, _, _, _ = _run_multitenant(params, cfg, False,
                                             only=("interactive",))
    inter_itl = _gap_p99(gaps_t, "interactive")
    inter_solo = _gap_p99(gaps_solo, "interactive")
    assert inter_itl <= 1.5 * inter_solo + 1.0, \
        (f"interactive p99 ITL {inter_itl} ticks exceeds 1.5x solo "
         f"({inter_solo} ticks): the noisy neighbor leaked through")
    noisy_t = _gap_p99(stalls_t, "noisy")
    noisy_u = _gap_p99(stalls_u, "noisy")
    assert noisy_t >= noisy_u, \
        (f"noisy tenant p99 stall improved under tenancy "
         f"({noisy_u} -> {noisy_t} ticks): the flood must pay, "
         "not profit")
    return (lambda: float(_gap_p99(stalls_t, "interactive"))), \
        (lambda: float(_gap_p99(stalls_u, "interactive")))


def _spec_decode_setup(on_tpu, spec_k, tracer=None):
    """Scheduler-driven decode over repetitive prompts (the n-gram
    drafter's home turf). Returns ``run() -> (tokens, stats)``: each
    call drains a FRESH scheduler over the same paged engine — the
    jitted prefill/verify stay warm after the first call, so timed
    calls measure the steady-state tick loop (host drafting, device
    verify, accept walk) and not compiles. ``spec_k=0`` builds the
    plain one-token-per-tick engine on the identical model/pool shape,
    which is what the ``decode_spec_vs_plain`` A/B pair races; a
    ``tracer`` rides through to the engine so the serving configs can
    report registry-derived latency percentiles (and so the
    ``decode_observed_vs_bare`` pair can price the hooks)."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  PagedDecodeEngine, Request)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    slots = 4
    max_new = 48 if on_tpu else 24
    eng = PagedDecodeEngine(params, cfg, num_slots=slots, max_len=128,
                            num_pages=128, page_size=8, buckets=(16,),
                            spec_k=spec_k, tracer=tracer)

    def run():
        sched = ContinuousBatchingScheduler(eng, eos_id=-1)
        for i in range(slots):
            # period-2 repetition: every suffix recurs, so the drafter
            # always has a continuation to propose
            sched.submit(Request(prompt=(5 + i, 7 + i) * 6,
                                 max_new_tokens=max_new))
        streams = sched.run()
        return sum(len(s) for s in streams), sched.stats

    return run, max_new * slots


def _natural_spec_setup(on_tpu, mode, spec_k=4, tracer=None):
    """Scheduler drain over a SEEDED NON-REPETITIVE workload — prompts
    drawn from a fixed PRNG over the whole vocab, so the n-gram
    drafter's suffix lookup has almost nothing to hit and any
    speculative win must come from the model drafter. ``mode`` picks
    the draft source: ``"ngram"`` (host prompt-lookup), ``"model"``
    (the lockstep DraftModel; the target doubles as its own drafter —
    the high-acceptance regime the r13 amortization math prices),
    ``"tree"`` (model drafts verified as a grid with the second-best
    root child riding along), ``"plain"`` (spec_k=0 baseline). Returns
    ``run() -> (committed_tokens, ticks, stats)``; as in
    ``_spec_decode_setup``, each call drains a fresh scheduler over the
    same warm engine."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  DraftModel, PagedDecodeEngine, Request)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    slots = 4
    max_new = 32 if on_tpu else 16
    kw = {}
    if mode != "plain":
        kw["spec_k"] = spec_k
    if mode in ("model", "tree"):
        kw["draft_model"] = DraftModel(params, cfg, num_slots=slots,
                                       max_len=128)
    if mode == "tree":
        kw["tree_spec"] = True
    eng = PagedDecodeEngine(params, cfg, num_slots=slots, max_len=128,
                            num_pages=128, page_size=8, buckets=(16,),
                            tracer=tracer, **kw)
    prompts = [tuple(int(t) for t in jax.random.randint(
        jax.random.PRNGKey(1234 + i), (12,), 0, cfg.vocab_size))
        for i in range(slots)]

    def run():
        sched = ContinuousBatchingScheduler(eng, eos_id=-1)
        for p in prompts:
            sched.submit(Request(prompt=p, max_new_tokens=max_new))
        streams = sched.run()
        st = sched.stats
        return (sum(len(s) for s in streams),
                st.spec_ticks + st.plain_ticks, st)

    return run, max_new * slots


def bench_gpt_spec_natural(on_tpu):
    """Driver metrics for the model-based speculation tier on the
    seeded non-repetitive stream (adversarial for prompt-lookup,
    natural for a model drafter): one line per drafting mode with the
    committed-token rate, the acceptance rate, and m̄ — mean committed
    tokens per tick, the quantity the r13 break-even condition bounds
    (m̄ > 1.017 + draft_bytes/target_bytes)."""
    from apex_tpu.serving import Tracer

    spec_k = 4
    for mode in ("ngram", "model", "tree"):
        metric = f"gpt_spec_natural_{mode}_accepted_tokens_per_s"
        try:
            trc = Tracer()
            run, expect = _natural_spec_setup(on_tpu, mode, spec_k,
                                              tracer=trc)
            run()  # compile prefill/verify + warm the draft path
            best = total = ticks = stats = None
            for _ in range(3 if on_tpu else 1):
                t0 = time.perf_counter()
                total, ticks, stats = run()
                dtr = time.perf_counter() - t0
                best = dtr if best is None else min(best, dtr)
            assert total == expect, (total, expect)
            extra = {"spec_k": spec_k, "tokens": total, "ticks": ticks,
                     "mean_committed_per_tick":
                         round(total / max(ticks, 1), 4),
                     "acceptance_rate":
                         stats.as_dict()["acceptance_rate"],
                     "tokens_drafted": stats.tokens_drafted,
                     "tokens_accepted": stats.tokens_accepted}
            # registry-derived tick-clock percentiles (ttft_p50/...,
            # itl_p50/... — deterministic, unlike the wall timings)
            extra.update(trc.latency_summary())
            _maybe_dump_trace(trc, f"spec_natural_{mode}")
            emit(metric, total / best, "tokens/sec", extra=extra)
        except Exception as e:  # one mode must never sink the others
            print(json.dumps({"metric": metric,
                              "error": repr(e)[:200]}), flush=True)


def _bench_spec_decode(on_tpu):
    """Emit ``gpt_spec_accepted_tokens_per_s``: end-to-end committed
    tokens/sec of the spec_k draft→verify→accept loop, with the
    acceptance rate the roofline math keys on in ``extra`` (BASELINE
    r11: the verify step beats plain paged decode on bytes per
    accepted token whenever expected commits/tick exceed ~1.017)."""
    from apex_tpu.serving import Tracer

    spec_k = 4
    trc = Tracer()
    run, expect = _spec_decode_setup(on_tpu, spec_k, tracer=trc)
    run()  # compile prefill/verify + warm the host draft path
    best, total, stats = None, 0, None
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        total, stats = run()
        dtr = time.perf_counter() - t0
        best = dtr if best is None else min(best, dtr)
    assert total == expect, (total, expect)  # eos_id=-1: full streams
    extra = {"spec_k": spec_k, "tokens": total,
             "acceptance_rate": stats.as_dict()["acceptance_rate"],
             "tokens_drafted": stats.tokens_drafted,
             "tokens_accepted": stats.tokens_accepted}
    extra.update(trc.latency_summary())
    _maybe_dump_trace(trc, "spec")
    emit("gpt_spec_accepted_tokens_per_s", total / best, "tokens/sec",
         extra=extra)


def bench_gpt_decode(on_tpu):
    body, make_init, fetch, slots, s_max, cfg = _decode_bench_setup(
        on_tpu, jnp.bfloat16)
    dt = timed(body, make_init, fetch, M=20 if on_tpu else 2,
               donate=True)
    metric = "gpt_decode_tokens_per_s"
    extra = {}
    # same run-went-off-the-rails gate as the headline: throughput
    # metrics can't reuse checked()'s time-scale comparison
    prior = [v for v in _recorded_values(metric) if v]
    from apex_tpu.utils.platform import has_tpu
    if prior and has_tpu():
        if not (1 / 3.0 < (slots / dt) / prior[-1] < 3.0):
            first = slots / dt
            dt = min(dt, timed(body, make_init, fetch, M=20,
                               donate=True))
            extra = {"retried": True, "first": round(first, 2)}
    extra.update({"slots": slots, "seq_max": s_max,
                  "cache_dtype": "bfloat16",
                  "per_token_latency_ms": round(dt * 1e3, 3)})
    try:
        (extra["model_bytes_per_token"], extra["kv_bytes_per_step"],
         extra["weight_bytes_per_token"]) = _decode_cost_numbers(
            cfg, slots, s_max // 2,
            jnp.bfloat16 if on_tpu else jnp.float32, jnp.bfloat16)
        # the int8 tree over the same program: the weight-read halving
        # the quantized tier banks on, priced next to the measured rate
        extra["weight_bytes_per_token_w8"] = _decode_cost_numbers(
            cfg, slots, s_max // 2,
            jnp.bfloat16 if on_tpu else jnp.float32, jnp.bfloat16,
            quantized=True)[2]
    except Exception as e:  # static cross-check must never sink the bench
        extra["model_bytes_per_token_error"] = repr(e)
    try:
        # degradation counters under a pinned fault schedule: proves
        # the graceful-degradation layer stays wired (faults surface as
        # typed outcomes and moving counters, not hangs or crashes)
        extra["serving_stats"] = _serving_stats_probe()
    except Exception as e:  # robustness probe must never sink the bench
        extra["serving_stats_error"] = repr(e)
    try:
        # tick-clock TTFT / inter-token percentiles from the tracer
        # registry: the observability layer's own export, tracked here
        # so a scheduling regression shows up as a latency shift even
        # when raw throughput holds
        extra.update(_observed_decode_probe())
    except Exception as e:  # observability probe must never sink it
        extra["observed_latency_error"] = repr(e)
    emit(metric, slots / dt, "tokens/sec", extra=extra)
    try:
        _bench_spec_decode(on_tpu)
    except Exception as e:  # spec config must never sink the headline
        print(json.dumps({"metric": "gpt_spec_accepted_tokens_per_s",
                          "error": repr(e)[:200]}), flush=True)


def _paged_vs_dense_decode_ab_pair(on_tpu):
    """(side_a, side_b): paged ragged-length decode vs the dense
    slots x S_max step — prices the length-proportional K/V read the
    page pool banks on. Same medium shape and uniform 32..512 ragged
    ladder as the ``gpt_paged_decode_step_medium_ragged`` cost entry
    (BASELINE r10), so the measured ratio lands next to the static
    ~40% K/V-read cut. ``active`` is all-False on BOTH sides: lengths
    never advance, so every scan iteration re-measures the same
    in-range program (no page-boundary host work inside the timed
    region); the argmax token feedback keeps the chain
    data-dependent. Params are closed over, not threaded — the
    non-donating A/B harness already holds two caches per side."""
    import dataclasses

    from apex_tpu.models.gpt import GPTConfig, gpt_tiny, init_gpt
    from apex_tpu.serving.cache import (
        NULL_PAGE, RESERVED_PAGES, init_cache, init_paged_cache,
        max_pages_per_slot,
    )
    from apex_tpu.serving.decode import (
        _decode_core, _dense, _embed_unsharded, _logits_unsharded,
        _paged_decode_core,
    )

    if on_tpu:
        cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        ffn_hidden_size=4096, vocab_size=50304,
                        max_position_embeddings=1024, use_rope=True,
                        hidden_dropout=0.0)
        slots, s_max, page = 32, 512, 64
        param_dtype = jnp.bfloat16
    else:
        cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                                  hidden_dropout=0.0)
        slots, s_max, page = 4, 64, 16
        param_dtype = jnp.float32
    lo = s_max // 16
    lengths = [lo + round(i * (s_max - lo) / (slots - 1))
               for i in range(slots)]
    params = init_gpt(jax.random.PRNGKey(0), cfg, param_dtype)
    embed = _embed_unsharded(cfg, None)
    lengths_arr = jnp.asarray(lengths, jnp.int32)
    active = jnp.zeros((slots,), bool)
    tokens0 = jnp.zeros((slots,), jnp.int32)
    M = 10 if on_tpu else 2
    fetch = lambda s: jnp.sum(s[1]).astype(jnp.float32)  # noqa: E731

    def paged_init():
        max_pages = max_pages_per_slot(s_max, page)
        # one mapped page run per slot, sized so the write row
        # (pos = length) is mapped; tails stay NULL (masked zeros)
        runs = [min(-(-(l + 1) // page), max_pages) for l in lengths]
        cache = init_paged_cache(cfg, slots, s_max,
                                 RESERVED_PAGES + sum(runs), page,
                                 jnp.bfloat16)
        rows, nxt = [], RESERVED_PAGES
        for n in runs:
            rows.append(list(range(nxt, nxt + n))
                        + [NULL_PAGE] * (max_pages - n))
            nxt += n
        return cache._replace(
            lengths=lengths_arr,
            block_tables=jnp.asarray(rows, jnp.int32))

    def body_a(state):
        cache, tokens = state
        cache, logits = _paged_decode_core(
            params, cfg, cache, tokens, active, embed_fn=embed,
            dense_fns=(_dense,) * 4, logits_fn=_logits_unsharded)
        return cache, jnp.argmax(logits, -1).astype(jnp.int32)

    def body_b(state):
        cache, tokens = state
        cache, logits = _decode_core(
            params, cfg, cache, tokens, active, embed_fn=embed,
            dense_fns=(_dense,) * 4, logits_fn=_logits_unsharded)
        return cache, jnp.argmax(logits, -1).astype(jnp.int32)

    dense_cache = init_cache(cfg, slots, s_max, jnp.bfloat16)._replace(
        lengths=lengths_arr)
    return (_ab_side(body_a, (paged_init(), tokens0), fetch, M),
            _ab_side(body_b, (dense_cache, tokens0), fetch, M))


def _spec_vs_plain_decode_ab_pair(on_tpu):
    """(side_a, side_b): the spec_k=4 draft→verify→accept scheduler
    drain vs the plain one-token-per-tick drain, identical model, pool
    shape and request stream, scored as SECONDS PER COMMITTED TOKEN.
    Unlike the kernel pairs this times the whole tick loop (host
    drafting + device verify + accept walk), because that is the unit
    the speculative claim is about: amortizing the parameter read only
    pays if the end-to-end committed-token rate rises. Ratio < 1 means
    the speculative path wins; the per-round pairing absorbs relay
    drift exactly as in the other pairs (the r6/r7 rule)."""
    def side(spec_k):
        run, _ = _spec_decode_setup(on_tpu, spec_k)
        run()  # compile + warm

        def sample():
            t0 = time.perf_counter()
            n, _ = run()
            return (time.perf_counter() - t0) / n

        return sample

    return side(4), side(0)


def _observed_vs_bare_decode_ab_pair(on_tpu):
    """(side_a, side_b): the plain scheduler drain with a live tracer
    vs the same drain with the inert default — prices the
    observability hooks themselves, scored as seconds per committed
    token. The no-op path is one attribute check per hook site (the
    fault-injector contract), so the honest expectation is a ratio
    indistinguishable from 1.0; this pair is the standing receipt. The
    traced side clears its event log each sample so list-append cost
    doesn't compound across rounds, and each sample takes the best of
    three drains — single full-drain timings on this pair swing +-15%
    with host noise, far above the effect being priced."""
    from apex_tpu.serving import Tracer

    def side(traced):
        trc = Tracer() if traced else None
        run, _ = _spec_decode_setup(on_tpu, 0, tracer=trc)
        run()  # compile + warm

        def sample():
            best = None
            for _ in range(3):
                if trc is not None:
                    trc.events.clear()
                    trc.recorder.clear()
                t0 = time.perf_counter()
                n, _ = run()
                dt = (time.perf_counter() - t0) / n
                best = dt if best is None else min(best, dt)
            return best

        return sample

    return side(True), side(False)


def _chunked_vs_monolithic_ab_pair(on_tpu):
    """(side_a, side_b): the chunked-prefill scheduler vs monolithic
    admission on the same seeded long-context Poisson mix (40-56-token
    prompts landing mid-decode — the head-of-line case), scored as P99
    INTER-TOKEN LATENCY IN SCHEDULER TICKS instead of wall seconds.
    The tick clock charges every forward its sequential depth, so a
    monolithic S-token prefill opens an ~S-tick gap in co-tenant
    streams while chunks bound the gap at the tick token budget; the
    committed streams are asserted bit-identical between the sides
    before either number is trusted — latency is the ONLY axis this
    pair is allowed to move. Both sides replay identical arrivals, so
    each sample is an exact replica and the band collapses to the
    point ratio. Ratio < 1 = chunking holds the bound."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  PagedDecodeEngine, Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)

    def side(chunk_tokens):
        trc = Tracer()
        eng = PagedDecodeEngine(params, cfg, num_slots=2, max_len=64,
                                num_pages=48, page_size=4,
                                buckets=(16, 64), tracer=trc)
        sched = ContinuousBatchingScheduler(eng, eos_id=-1,
                                            chunk_tokens=chunk_tokens)
        streams = _drive_poisson(
            sched, _scenario_arrivals("long_context", cfg.vocab_size))
        lat = trc.latency_summary()
        return streams, lat, (lambda: float(lat["itl_p99"]))

    streams_a, lat_a, sample_a = side(8)
    streams_b, lat_b, sample_b = side(None)
    assert streams_a == streams_b, "chunked streams diverged"
    # deferring prompt work costs some TTFT; the contract is that the
    # cost stays bounded while the ITL tail collapses
    assert lat_a["ttft_p50"] <= 2.0 * lat_b["ttft_p50"] + 1.0, \
        (lat_a["ttft_p50"], lat_b["ttft_p50"])
    return sample_a, sample_b


def _disagg_vs_colocated_ab_pair(on_tpu):
    """(side_a, side_b): the disaggregated prefill/decode router vs
    the colocated scheduler on the same seeded long-context Poisson
    mix, scored as P99 INTER-TOKEN LATENCY IN SCHEDULER TICKS. The
    colocated side charges every admission prefill its sequential
    depth — a 40-56-token prompt landing mid-decode opens an ~S-tick
    gap in every co-tenant stream. The router runs that forward on the
    PREFILL replica, concurrent with decode, and charges only the
    deterministic page-handoff cost (~1 tick per prompt here), so the
    co-tenant gap collapses: the DistServe/Mooncake prefill-decode
    interference argument on the tick clock. The committed streams are
    asserted bit-identical between the sides before either number is
    trusted — latency is the ONLY axis disaggregation may move. Both
    sides replay identical arrivals, so each sample is an exact
    replica and the band collapses to the point ratio. Ratio < 1 =
    the split removes the interference."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  DisaggregatedRouter, FaultInjector,
                                  PagedDecodeEngine, Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)

    def engine(trc, inj=None):
        return PagedDecodeEngine(params, cfg, num_slots=2, max_len=64,
                                 num_pages=48, page_size=4,
                                 buckets=(16, 64), tracer=trc,
                                 injector=inj)

    def side(disagg):
        trc = Tracer()
        if disagg:
            inj = FaultInjector()  # one injector, shared — inert
            sched = DisaggregatedRouter(engine(trc, inj),
                                        engine(trc, inj), eos_id=-1)
        else:
            sched = ContinuousBatchingScheduler(engine(trc), eos_id=-1)
        streams = _drive_poisson(
            sched, _scenario_arrivals("long_context", cfg.vocab_size))
        lat = trc.latency_summary()
        return streams, lat, (lambda: float(lat["itl_p99"]))

    streams_a, lat_a, sample_a = side(True)
    streams_b, lat_b, sample_b = side(False)
    assert streams_a == streams_b, "disaggregated streams diverged"
    return sample_a, sample_b


def _pool_2x2_vs_1x1_ab_pair(on_tpu):
    """(side_a, side_b): a 2x2 replica pool riding the device-to-device
    reshard tier (ICI-priced, 0.03125 ticks/page, link-overlap clock)
    vs the single-pair router's host-staged handoff (0.125 ticks/page,
    serial), both draining the seeded long-context mix as a CLOSED-LOOP
    BURST (every request queued at tick 0 — an open-loop Poisson replay
    hides the handoff charge inside idle inter-arrival gaps that
    ``advance_clock`` jumps over), scored as TICKS PER COMMITTED
    TOKEN — the inverse goodput, so the point ratio IS the goodput
    ratio with the sides flipped. The
    committed streams are asserted bit-identical between the pool and
    the pair before either clock is read (routing, resharding and
    placement may only move the clock), and the pool's final clock is
    asserted <= the pair's — the per-link pricing claim (a 14-page
    long-context prompt charges ceil(14 x 0.03125) = 1 ICI tick vs
    ceil(14 x 0.125) = 2 host-staged ticks) made load-bearing. Both
    sides replay identical arrivals, so the band collapses to the
    point ratio. Ratio < 1 = the pool's resharded handoff is cheaper
    per token."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (DisaggregatedRouter, FaultInjector,
                                  PagedDecodeEngine, PoolRouter,
                                  Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)

    def engine(trc, inj):
        return PagedDecodeEngine(params, cfg, num_slots=2, max_len=64,
                                 num_pages=48, page_size=4,
                                 buckets=(16, 64), tracer=trc,
                                 injector=inj)

    def side(pool):
        trc = Tracer()
        inj = FaultInjector()          # one injector, shared — inert
        if pool:
            sched = PoolRouter([engine(trc, inj) for _ in range(2)],
                               [engine(trc, inj) for _ in range(2)],
                               eos_id=-1)
        else:
            sched = DisaggregatedRouter(engine(trc, inj),
                                        engine(trc, inj), eos_id=-1)
        for _, req in _scenario_arrivals("long_context",
                                         cfg.vocab_size):
            sched.submit(req)
        while sched.busy:
            sched.step()
        streams = [list(sched.outcomes[rid].tokens)
                   for rid in sorted(sched.outcomes)]
        tokens = sum(len(s) for s in streams)
        tpt = sched.clock / max(1, tokens)
        return streams, sched.clock, (lambda: float(tpt))

    streams_a, clock_a, sample_a = side(True)
    streams_b, clock_b, sample_b = side(False)
    assert streams_a == streams_b, "pool streams diverged from pair"
    assert clock_a <= clock_b, \
        (f"resharded pool clock {clock_a} exceeds host-staged pair "
         f"clock {clock_b}: per-link pricing regressed")
    return sample_a, sample_b


def _host_hit_vs_reprefill_ab_pair(on_tpu):
    """(side_a, side_b): admitting a hot prompt whose pages live in the
    HOST TIER (a prefix-registry hit: promote + suffix prefill) vs
    re-prefilling it from scratch, scored as TTFT IN SCHEDULER TICKS.
    A promotion charges transfer ticks while the forward runs only the
    uncovered suffix's sequential depth, so the win is pinned at the
    depth ratio: with a 16-token prompt, 12 covered tokens and 1
    promote tick, side B must pay >= 16/5 x side A's TTFT — asserted,
    not just reported. Before timing, committed streams are asserted
    bit-identical to the spill-disabled scheduler across greedy +
    sampled, spec off/on, and through the disaggregated router pair
    sharing one registry — the hierarchy may only move the clock.
    Ratio < 1 = the host tier beats re-prefill."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  DisaggregatedRouter, FaultInjector,
                                  PagedDecodeEngine, PrefixRegistry,
                                  Request, Tracer)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    hot = tuple(range(7, 23))               # 16 tokens = 4 pages

    def engine(tier, trc=None, inj=None, spec_k=0):
        return PagedDecodeEngine(params, cfg, num_slots=2, max_len=32,
                                 num_pages=12, page_size=4,
                                 buckets=(16, 32), spec_k=spec_k,
                                 tracer=trc or Tracer(), injector=inj,
                                 host_tier=tier)

    def primed_tier():
        """A registry holding the hot prompt's full chain: prefill it
        once, release, and drain the pool so every page spills."""
        tier = PrefixRegistry(1 << 20)
        eng = engine(tier)
        eng.prefill(0, hot)
        eng.free_slot(0)
        while eng.pool.alloc() is not None:
            pass
        assert eng.stats.host_spills == 4, eng.stats.host_spills
        return tier

    def run(tier, temperature=0.0, spec_k=0, disagg=False):
        trc = Tracer()
        if disagg:
            inj = FaultInjector()
            sched = DisaggregatedRouter(
                engine(tier, trc, inj, spec_k),
                engine(tier, trc, inj, spec_k), eos_id=-1)
        else:
            sched = ContinuousBatchingScheduler(
                engine(tier, trc, spec_k=spec_k), eos_id=-1)
        sched.submit(Request(prompt=hot, max_new_tokens=4,
                             temperature=temperature, seed=5))
        sched.run()
        out = sched.outcomes[0]
        return list(out.tokens), float(out.ttft_ticks)

    # bit-identity sweep: the hierarchy must not move a single token
    for kw in ({}, {"temperature": 1.0}, {"spec_k": 2},
               {"disagg": True}):
        streams_a, _ = run(primed_tier(), **kw)
        streams_b, _ = run(None, **kw)
        assert streams_a == streams_b, \
            f"host-tier streams diverged under {kw or 'greedy'}"

    streams_a, ttft_a = run(primed_tier())
    streams_b, ttft_b = run(None)
    assert streams_a == streams_b
    covered, promote_ticks = 12, 1          # skip 3 of 4 pages
    depth_ratio = len(hot) / (len(hot) - covered + promote_ticks)
    assert ttft_b >= ttft_a * depth_ratio, \
        (ttft_a, ttft_b, depth_ratio)
    return (lambda: ttft_a), (lambda: ttft_b)


def _decode_cache_ab_pair(on_tpu):
    """(side_a, side_b): bf16 vs fp32 KV cache on the batched decode
    step — prices the cache-HBM halving the serving default banks on.
    Smaller slot count than the driver metric: the non-donating A/B
    harness holds both sides' caches (and two copies each) live."""
    def side(dtype):
        body, make_init, fetch, _, _, _ = _decode_bench_setup(
            on_tpu, dtype, slots=8 if on_tpu else 2)
        return _ab_side(body, make_init(), fetch, M=10 if on_tpu else 2)

    return side(jnp.bfloat16), side(jnp.float32)


def _w8_decode_ab_pair(on_tpu):
    """(side_a, side_b): weight-only int8 decode (dequant-fused Pallas
    matmuls, fp32 scales) vs the bf16 dense step — same model, cache
    depth and token feedback, so the ratio prices the parameter-read
    halving on the measured step rather than the static table. The
    cache stays bf16 on BOTH sides: this pair isolates the weight
    axis; ``decode_kv8_vs_bf16`` isolates the cache axis."""
    import dataclasses

    from apex_tpu.models.gpt import GPTConfig, gpt_tiny, init_gpt
    from apex_tpu.quant.params import quantize_params
    from apex_tpu.serving.cache import init_cache
    from apex_tpu.serving.decode import _decode_core, _unsharded_fns

    if on_tpu:
        cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        ffn_hidden_size=4096, vocab_size=50304,
                        max_position_embeddings=1024, use_rope=True,
                        hidden_dropout=0.0)
        slots, s_max = 16, 256
        param_dtype = jnp.bfloat16
    else:
        cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                                  hidden_dropout=0.0)
        slots, s_max = 2, 32
        param_dtype = jnp.float32
    params = init_gpt(jax.random.PRNGKey(0), cfg, param_dtype)
    active = jnp.zeros((slots,), bool)
    tokens0 = jnp.zeros((slots,), jnp.int32)
    M = 10 if on_tpu else 2
    fetch = lambda s: jnp.sum(s[1]).astype(jnp.float32)  # noqa: E731

    def side(p, quantized):
        embed, dense_fns, logits_fn = _unsharded_fns(cfg, None, quantized)

        def body(state, p=p):
            cache, tokens = state
            cache, logits = _decode_core(
                p, cfg, cache, tokens, active, embed_fn=embed,
                dense_fns=dense_fns, logits_fn=logits_fn)
            return cache, jnp.argmax(logits, -1).astype(jnp.int32)

        cache = init_cache(cfg, slots, s_max, jnp.bfloat16)._replace(
            lengths=jnp.full((slots,), s_max // 2, jnp.int32))
        return _ab_side(body, (cache, tokens0), fetch, M)

    return side(quantize_params(params), True), side(params, False)


def _kv8_decode_ab_pair(on_tpu):
    """(side_a, side_b): int8 page pool (per-page-per-head fp32 scales,
    whole-page RMW requant on write) vs the bf16 pool on the paged
    ragged decode — bf16 weights on BOTH sides, so the ratio prices the
    cache-read halving net of the requant read-modify-write the int8
    write path adds. Same ragged ladder as ``decode_paged_vs_dense``."""
    import dataclasses

    from apex_tpu.models.gpt import GPTConfig, gpt_tiny, init_gpt
    from apex_tpu.serving.cache import (
        NULL_PAGE, RESERVED_PAGES, init_paged_cache, max_pages_per_slot,
    )
    from apex_tpu.serving.decode import (
        _dense, _embed_unsharded, _logits_unsharded, _paged_decode_core,
    )

    if on_tpu:
        cfg = GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        ffn_hidden_size=4096, vocab_size=50304,
                        max_position_embeddings=1024, use_rope=True,
                        hidden_dropout=0.0)
        slots, s_max, page = 32, 512, 64
        param_dtype = jnp.bfloat16
    else:
        cfg = dataclasses.replace(gpt_tiny(), use_rope=True,
                                  hidden_dropout=0.0)
        slots, s_max, page = 4, 64, 16
        param_dtype = jnp.float32
    lo = s_max // 16
    lengths = [lo + round(i * (s_max - lo) / (slots - 1))
               for i in range(slots)]
    params = init_gpt(jax.random.PRNGKey(0), cfg, param_dtype)
    embed = _embed_unsharded(cfg, None)
    lengths_arr = jnp.asarray(lengths, jnp.int32)
    active = jnp.zeros((slots,), bool)
    tokens0 = jnp.zeros((slots,), jnp.int32)
    M = 10 if on_tpu else 2
    fetch = lambda s: jnp.sum(s[1]).astype(jnp.float32)  # noqa: E731

    def paged_init(dtype):
        max_pages = max_pages_per_slot(s_max, page)
        runs = [min(-(-(l + 1) // page), max_pages) for l in lengths]
        cache = init_paged_cache(cfg, slots, s_max,
                                 RESERVED_PAGES + sum(runs), page, dtype)
        rows, nxt = [], RESERVED_PAGES
        for n in runs:
            rows.append(list(range(nxt, nxt + n))
                        + [NULL_PAGE] * (max_pages - n))
            nxt += n
        return cache._replace(
            lengths=lengths_arr,
            block_tables=jnp.asarray(rows, jnp.int32))

    def side(dtype):
        def body(state):
            cache, tokens = state
            cache, logits = _paged_decode_core(
                params, cfg, cache, tokens, active, embed_fn=embed,
                dense_fns=(_dense,) * 4, logits_fn=_logits_unsharded)
            return cache, jnp.argmax(logits, -1).astype(jnp.int32)

        return _ab_side(body, (paged_init(dtype), tokens0), fetch, M)

    return side(jnp.int8), side(jnp.bfloat16)


def _w8kv8_spec_ab_pair(on_tpu):
    """(side_a, side_b): the spec_k=4 draft→verify→accept scheduler
    drain with the FULL quantized tier (int8 weights + int8 page pool)
    vs the same drain at bf16, scored as seconds per committed token —
    does the byte saving survive the end-to-end tick loop (host
    drafting + dequant-fused verify + accept walk), or does the requant
    RMW eat it at this scale."""
    import dataclasses as _dc

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.quant.params import quantize_params
    from apex_tpu.serving import (ContinuousBatchingScheduler,
                                  PagedDecodeEngine, Request)

    cfg = _dc.replace(gpt_tiny(), use_rope=True, hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(0), cfg)
    slots = 4
    max_new = 48 if on_tpu else 24

    def side(quantized):
        eng = PagedDecodeEngine(
            quantize_params(params) if quantized else params, cfg,
            num_slots=slots, max_len=128, num_pages=128, page_size=8,
            buckets=(16,), spec_k=4,
            cache_dtype=jnp.int8 if quantized else jnp.bfloat16)

        def run():
            sched = ContinuousBatchingScheduler(eng, eos_id=-1)
            for i in range(slots):
                sched.submit(Request(prompt=(5 + i, 7 + i) * 6,
                                     max_new_tokens=max_new))
            return sum(len(s) for s in sched.run())

        run()  # compile prefill/verify + warm the host draft path

        def sample():
            t0 = time.perf_counter()
            n = run()
            return (time.perf_counter() - t0) / n

        return sample

    return side(True), side(False)


def _spec_tree_vs_linear_ab_pair(on_tpu):
    """(side_a, side_b): tree-grid drafts (greedy chain + second-best
    root child, verified in ONE forward through the ancestor-matrix
    mask) vs linear chain drafts from the SAME lockstep DraftModel over
    the same seeded non-repetitive stream, scored as seconds per
    committed token. Prices exactly the tree claim: when the chain's
    first token is wrong, the grid's alternate root child keeps a
    commit the linear draft loses — at the cost of k1·k2 verify
    columns instead of k."""
    def side(tree):
        run, _ = _natural_spec_setup(on_tpu, "tree" if tree else "model")
        run()  # compile prefill/verify + warm the draft path

        def sample():
            t0 = time.perf_counter()
            n, _, _ = run()
            return (time.perf_counter() - t0) / n

        return sample

    return side(True), side(False)


# -- flash-attention microbench: kernel vs unfused at long seq --------------

def bench_flash_attention(on_tpu):
    """fwd+bwd at seq 2048 (b·h·s·d sized for one chip): the Pallas
    kernel vs XLA's materialized-scores path — the dispatch-crossover
    evidence (flash_attention.py picks the kernel above seq 256)."""
    from apex_tpu.transformer.functional import flash_attention

    b, h, s, d = (4, 16, 2048, 64) if on_tpu else (1, 2, 256, 16)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
               for kk in ks)

    kernel_2048_ms = None
    for name, use_kernel in (("kernel", True), ("unfused", False)):
        def body(q, uk=use_kernel):
            g = jax.grad(lambda q: jnp.sum(flash_attention(
                q, k, v, causal=True, use_kernel=uk).astype(jnp.float32)
                ** 2))(q)
            return (g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-6)).astype(
                q.dtype)

        metric = f"flash_attention_{name}_seq{s}_fwdbwd"
        dt, extra = checked(metric, 1e3, body, q,
                            lambda x: jnp.sum(x.astype(jnp.float32)),
                            M=10 if on_tpu else 2)
        if use_kernel:
            kernel_2048_ms = dt * 1e3
        # causal attention FLOPs: ~2·(QK + PV + bwd≈2.5x) over s²/2
        flops = 2 * 3.5 * b * h * s * s * d
        extra["tflops"] = round(flops / dt / 1e12, 1)
        emit(metric, dt * 1e3, "ms/iter", extra=extra,
             higher_is_better=False)

    # long-seq causal line (kernel only: materialized scores at 4096 would
    # need a 4.3 GB fp32 tensor; b halved to keep the working set fair)
    b2, s2 = (2, 4096) if on_tpu else (1, 512)
    q2, k2, v2 = (jax.random.normal(kk, (b2, h, s2, d), jnp.bfloat16)
                  for kk in ks)

    def body2(q2):
        g = jax.grad(lambda q2: jnp.sum(flash_attention(
            q2, k2, v2, causal=True, use_kernel=True).astype(jnp.float32)
            ** 2))(q2)
        return (g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-6)).astype(q2.dtype)

    # d=128 line: the MXU-full datapoint. d=64 fills half the 128-wide
    # systolic contraction for QK^T / dp=do@v^T; comparing achieved
    # TFLOPs here against the d=64 line separates "kernel is the
    # limiter" from "head shape is the limiter".
    h3, d3 = 8, 128  # same b*h*s*d working set as the d=64 line
    q3, k3, v3 = (jax.random.normal(kk, (b, h3, s, d3), jnp.bfloat16)
                  for kk in ks)

    def body3(q3):
        g = jax.grad(lambda q3: jnp.sum(flash_attention(
            q3, k3, v3, causal=True, use_kernel=True).astype(jnp.float32)
            ** 2))(q3)
        return (g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-6)).astype(q3.dtype)

    metric = f"flash_attention_kernel_seq{s}_d{d3}_fwdbwd"
    dt, extra = checked(metric, 1e3, body3, q3,
                        lambda x: jnp.sum(x.astype(jnp.float32)),
                        M=10 if on_tpu else 2)
    extra["tflops"] = round(2 * 3.5 * b * h3 * s * s * d3 / dt / 1e12, 1)
    emit(metric, dt * 1e3, "ms/iter", extra=extra, higher_is_better=False)

    metric = f"flash_attention_kernel_seq{s2}_fwdbwd"
    dt, extra = checked(metric, 1e3, body2, q2,
                        lambda x: jnp.sum(x.astype(jnp.float32)),
                        M=10 if on_tpu else 2)
    flops = 2 * 3.5 * b2 * h * s2 * s2 * d
    # Cross-metric sanity (BENCH_r04's tell): seq2048 runs HALF of
    # seq4096's FLOPs (b·s² ratio: 4·2048² vs 2·4096² = 1:2) so its
    # per-iter time must be LOWER; if not, the seq2048 number was
    # relay-damaged.
    if on_tpu and kernel_2048_ms is not None and kernel_2048_ms > dt * 1e3:
        print(json.dumps({"metric": "flash_sanity_seq2048_vs_seq4096",
                          "violated": True,
                          "seq2048_ms": round(kernel_2048_ms, 2),
                          "seq4096_ms": round(dt * 1e3, 2)}), flush=True)
    extra["tflops"] = round(flops / dt / 1e12, 1)
    emit(metric, dt * 1e3, "ms/iter", extra=extra, higher_is_better=False)


# -- same-process A/B harness -----------------------------------------------
#
# Cross-process runs of the SAME program drift ±15-20% through the relay
# (the LN h1024 thread: 88 µs one round, 80.7 µs the next, no code
# change), so any claim smaller than ~20% is unresolvable from two
# separate bench rounds. The ab harness closes that: both variants are
# compiled in ONE process and their samples interleave A,B,A,B,... so
# every drift regime that hits A also hits B, and the RATIO distribution
# is tight even when the absolute times wander.

def _ab_side(body, init_state, fetch, M, ctx=None):
    """Compile + warm one A/B side; returns ``sample() -> sec/iter``.

    One sample is a full chain-differenced measurement — run(1) and
    run(5) back-to-back, ``((t5 - t1) / 4M`` with the relay's fixed
    dispatch+fetch cost cancelling exactly as in ``timed`` — so each
    element of the ratio distribution is itself relay-calibrated.

    ``ctx`` (e.g. ``flash_attention.kernel_variant(exp2=False)``) wraps
    the jit TRACE + warm-up call: variant toggles are module globals
    read at trace time, so the compiled program bakes the variant in and
    the context can close before any measurement happens."""
    def chunk_body(state):
        def f(s, _):
            return body(s), ()
        s, _ = jax.lax.scan(f, state, None, length=M)
        return s

    chunk = jax.jit(chunk_body)

    def run(ncalls):
        state = chunk(init_state)
        for _ in range(ncalls - 1):
            state = chunk(state)
        float(fetch(state))

    with (ctx if ctx is not None else contextlib.nullcontext()):
        run(5)  # trace (under ctx) + compile + warm

    def sample():
        t0 = time.perf_counter()
        run(1)
        t1 = time.perf_counter()
        run(5)
        t2 = time.perf_counter()
        return max((t2 - t1) - (t1 - t0), 1e-9) / (4 * M)

    return sample


def ab_timed(side_a, side_b, rounds=5):
    """Interleaved A/B: ``rounds`` alternating samples per side.

    Returns (a_med, b_med, ratio_med, ratio_lo, ratio_hi) where the
    ratio stats come from the PER-ROUND a/b pairs (each pair shares a
    drift regime) — not from the two medians."""
    pairs = []
    for _ in range(rounds):
        a = side_a()
        b = side_b()
        pairs.append((a, b))
    ratios = sorted(a / b for a, b in pairs)
    return (statistics.median(p[0] for p in pairs),
            statistics.median(p[1] for p in pairs),
            statistics.median(ratios), ratios[0], ratios[-1])


def _flash_mod():
    # the package __init__ rebinds the name ``flash_attention`` to the
    # FUNCTION; importlib is the only way to address the module (where
    # kernel_variant and the toggles live)
    return importlib.import_module(
        "apex_tpu.transformer.functional.flash_attention")


def _flash_ab_pair(on_tpu, **toggles_b):
    """(side_a, side_b) for the d=64 fwd+bwd flash workload: A = shipped
    kernel configuration, B = ``kernel_variant(**toggles_b)``. Same
    shapes as the flash_attention_kernel_seq2048_fwdbwd driver metric so
    the ratio prices exactly the headline d=64 claim."""
    fam = _flash_mod()
    b, h, s, d = (4, 16, 2048, 64) if on_tpu else (1, 2, 256, 16)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
               for kk in ks)

    def body(q):
        g = jax.grad(lambda q: jnp.sum(fam.flash_attention(
            q, k, v, causal=True, use_kernel=True).astype(jnp.float32)
            ** 2))(q)
        return (g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-6)).astype(q.dtype)

    fetch = lambda x: jnp.sum(x.astype(jnp.float32))  # noqa: E731
    M = 10 if on_tpu else 2
    return (_ab_side(body, q, fetch, M),
            _ab_side(body, q, fetch, M, ctx=fam.kernel_variant(**toggles_b)))


def _ln_ab_pair(on_tpu):
    """(side_a, side_b) for the LN h=1024 fwd+bwd thread: A = fused
    Pallas kernel, B = the plain-jnp reference. Settles the r4/r5
    88-vs-80.7 µs question: those were CROSS-process readings of the
    same kernel; this measures kernel-vs-jnp in one process."""
    from apex_tpu.normalization import fused_layer_norm_affine

    rows, h = (8192, 1024) if on_tpu else (64, 256)
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, h), jnp.bfloat16)
    w = jnp.full((h,), 0.9, jnp.float32)
    b = jnp.zeros((h,), jnp.float32)

    def ln_ref(x):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, -1, keepdims=True)
        var = jnp.mean((xf - mu) ** 2, -1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * w + b
        return y.astype(x.dtype)

    def make_body(f):
        def body(dy):
            return jax.grad(
                lambda x: jnp.sum(f(x).astype(jnp.float32)
                                  * dy.astype(jnp.float32)))(x)
        return body

    dy0 = jax.random.normal(jax.random.PRNGKey(1), (rows, h), jnp.bfloat16)
    fetch = lambda s: jnp.sum(s.astype(jnp.float32))  # noqa: E731
    M = 400 if on_tpu else 2
    return (_ab_side(make_body(
                lambda x: fused_layer_norm_affine(x, w, b, h, 1e-5)),
                dy0, fetch, M),
            _ab_side(make_body(ln_ref), dy0, fetch, M))


def _adam_state_params(on_tpu):
    """Synthetic Adam working set: ~64M params on TPU (16 x 2048^2 —
    big enough that the step is HBM-bound, small enough that two
    optimizer states never coexist across ab sides' builds)."""
    n, dim = (16, 2048) if on_tpu else (4, 128)
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    params = {f"t{i}": jax.random.normal(k, (dim, dim)) for i, k in
              enumerate(keys)}
    grads = jax.tree.map(lambda p: jnp.full_like(p, 1e-4), params)
    return params, grads


def _adam_m_bf16_ab_pair(on_tpu):
    """bf16 vs fp32 first moment on the flat Adam kernel: the m
    read+write drops from 8 to 4 bytes/element, ~1/6 of the kernel's
    HBM traffic (g+p+m+v in, p+m+v out)."""
    from apex_tpu.optimizers import FusedAdam

    params, grads = _adam_state_params(on_tpu)
    M = 20 if on_tpu else 2
    fetch = lambda s: jnp.sum(s[0]["t0"])  # noqa: E731
    sides = []
    for m_dtype in (jnp.bfloat16, jnp.float32):
        opt = FusedAdam(lr=1e-4, weight_decay=0.01, use_flat_kernel=True,
                        m_dtype=m_dtype)

        def body(state, opt=opt):
            p, s = state
            return opt.step(grads, p, s)

        sides.append(_ab_side(body, (params, opt.init(params)), fetch, M))
    return tuple(sides)


def _adam_castout_ab_pair(on_tpu):
    """Fused bf16 cast-out vs the separate ``model_params_from_master``
    pass: both sides produce (params, state, bf16 compute tree) per
    step; side B pays an extra fp32 read of the whole master tree."""
    from apex_tpu.amp import policy
    from apex_tpu.optimizers import FusedAdam

    params, grads = _adam_state_params(on_tpu)
    compute = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    M = 20 if on_tpu else 2
    fetch = lambda s: jnp.sum(s[2]["t0"].astype(jnp.float32))  # noqa: E731

    opt_a = FusedAdam(lr=1e-4, weight_decay=0.01,
                      emit_compute_params=True)

    def body_a(state):
        p, s, c = state
        return opt_a.step(grads, p, s, compute_params=c)

    opt_b = FusedAdam(lr=1e-4, weight_decay=0.01)

    def body_b(state):
        p, s, c = state
        p, s = opt_b.step(grads, p, s)
        return p, s, policy.model_params_from_master(p, c)

    return (_ab_side(body_a, (params, opt_a.init(params), compute),
                     fetch, M),
            _ab_side(body_b, (params, opt_b.init(params), compute),
                     fetch, M))


def _small_tensor_pollution_pair(on_tpu):
    """SEQUENTIAL instrument for the small-tensor Adam driver drift
    (0.94 -> 1.35 -> 1.43 ms over r3-r5): measure the
    fused_adam_tree_1024_small_tensors body in a FRESH process regime
    (side A), then replay the process-global state the driver builds up
    before that metric runs — the headline train-step compile+run and a
    batch of kernel-parity style compilations — and measure again (side
    B). Interleaved ab can't isolate this (pollution is irreversible),
    so the entry is flagged "sequential" and returns (side_a,
    make_side_b); bench_ab drains A before building B."""
    import dataclasses

    from apex_tpu.models import bert_large, bert_tiny
    from apex_tpu.optimizers import FusedAdam

    n = 1024 if on_tpu else 32
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    params = {f"t{i}": jax.random.normal(k, (64, 128)) for i, k in
              enumerate(keys)}
    grads = jax.tree.map(lambda p: jnp.full_like(p, 1e-4), params)
    M = 20 if on_tpu else 2
    fetch = lambda s: jnp.sum(s[0]["t0"])  # noqa: E731

    def make_side():
        opt = FusedAdam(lr=1e-4, weight_decay=0.01)

        def body(state, opt=opt):
            p, s = state
            return opt.step(grads, p, s)

        return _ab_side(body, (params, opt.init(params)), fetch, M)

    def pollute():
        # the two configs that precede the small-tensor metric in the
        # driver's ORDER, run silently (no emit — these throwaway
        # numbers must not enter the metric record)
        cfg = bert_large() if on_tpu else bert_tiny()
        cfg = dataclasses.replace(cfg, remat=False)
        batch, seq = (64, 128) if on_tpu else (2, 64)
        train_step, make_state, (ids, mask) = _bert_step(batch, seq, cfg)
        st = jax.jit(train_step)(*make_state(), ids, mask)
        jax.block_until_ready(st[-1])
        del st
        bench_kernel_parity(on_tpu, quiet=True)

    def make_side_b():
        pollute()
        return make_side()

    return make_side(), make_side_b


# name -> (label_a, label_b, builder(on_tpu) -> (side_a, side_b)).
# ratio < 1 means A (the shipped configuration) wins.
# A 4th element "sequential" marks order-dependent pairs: the builder
# returns (side_a, make_side_b) and bench_ab drains every A sample
# BEFORE building B (whose build irreversibly mutates process state).
AB_PAIRS = {
    "flash_d64_exp2": (
        "exp2", "exp",
        lambda on_tpu: _flash_ab_pair(on_tpu, exp2=False)),
    "flash_d64_p32": (
        "p_bf16", "p_fp32",
        lambda on_tpu: _flash_ab_pair(on_tpu, p_bf16=False)),
    "flash_d64_block256": (
        "block512", "block256",
        lambda on_tpu: _flash_ab_pair(on_tpu, small_d_max_block=256)),
    "ln_h1024": (
        "fused_kernel", "jnp_ref",
        lambda on_tpu: _ln_ab_pair(on_tpu)),
    "adam_m_bf16": (
        "m_bf16", "m_fp32",
        _adam_m_bf16_ab_pair),
    "adam_castout": (
        "fused_castout", "separate_cast",
        _adam_castout_ab_pair),
    "adam_small_tensors_pollution": (
        "fresh", "polluted",
        _small_tensor_pollution_pair, "sequential"),
    "decode_cache_bf16": (
        "cache_bf16", "cache_fp32",
        _decode_cache_ab_pair),
    "decode_paged_vs_dense": (
        "paged_ragged", "dense_slots_x_smax",
        _paged_vs_dense_decode_ab_pair),
    "decode_spec_vs_plain": (
        "spec_k4", "plain",
        _spec_vs_plain_decode_ab_pair),
    "decode_observed_vs_bare": (
        "trace_on", "noop_hooks",
        _observed_vs_bare_decode_ab_pair),
    "prefill_chunked_vs_monolithic": (
        "chunked_budget", "monolithic",
        _chunked_vs_monolithic_ab_pair),
    "serving_disagg_vs_colocated": (
        "disagg_router", "colocated",
        _disagg_vs_colocated_ab_pair),
    "serving_pool_2x2_vs_1x1": (
        "pool_2x2", "disagg_1x1",
        _pool_2x2_vs_1x1_ab_pair),
    "prefix_host_hit_vs_reprefill": (
        "host_tier_hit", "reprefill",
        _host_hit_vs_reprefill_ab_pair),
    "decode_w8_vs_bf16": (
        "w8_weights", "bf16_weights",
        _w8_decode_ab_pair),
    "decode_kv8_vs_bf16": (
        "kv8_pool", "bf16_pool",
        _kv8_decode_ab_pair),
    "decode_w8kv8_spec": (
        "w8kv8_spec_k4", "bf16_spec_k4",
        _w8kv8_spec_ab_pair),
    "spec_tree_vs_linear": (
        "tree_grid", "linear_chain",
        _spec_tree_vs_linear_ab_pair),
    "serving_tenancy_vs_untenanted": (
        "tenanted_fair_share", "untenanted_fifo",
        _tenancy_vs_untenanted_ab_pair),
}


def bench_ab(on_tpu, names=None):
    """Run the A/B pairs registry; one JSON line per pair. Driver config
    name: ``ab_kernels``. CLI: ``python bench.py ab [pair ...]``.

    "sequential" entries (order-dependent process state) drain all A
    samples, then call the builder's second return (a thunk whose build
    mutates the process) and drain B — the per-round pairing survives,
    but A/B no longer share a drift regime, which is the point."""
    for name in (names or AB_PAIRS):
        if name not in AB_PAIRS:
            print(json.dumps({"metric": f"ab_{name}",
                              "error": "unknown ab pair"}), flush=True)
            continue
        entry = AB_PAIRS[name]
        label_a, label_b, build = entry[:3]
        sequential = len(entry) > 3 and entry[3] == "sequential"
        try:
            rounds = 5 if on_tpu else 2
            if sequential:
                side_a, make_side_b = build(on_tpu)
                a_samples = [side_a() for _ in range(rounds)]
                side_b = make_side_b()
                b_samples = [side_b() for _ in range(rounds)]
                pairs = list(zip(a_samples, b_samples))
                ratios = sorted(a / b for a, b in pairs)
                a_med = statistics.median(a_samples)
                b_med = statistics.median(b_samples)
                r_med, r_lo, r_hi = (statistics.median(ratios),
                                     ratios[0], ratios[-1])
            else:
                side_a, side_b = build(on_tpu)
                a_med, b_med, r_med, r_lo, r_hi = ab_timed(
                    side_a, side_b, rounds=rounds)
        except Exception as e:
            print(json.dumps({"metric": f"ab_{name}",
                              "error": repr(e)[:200]}), flush=True)
            continue
        decided = r_hi < 1.0 or r_lo > 1.0  # band excludes 1.0
        emit(f"ab_{name}", r_med, f"t({label_a})/t({label_b})",
             extra={"band": [round(r_lo, 4), round(r_hi, 4)],
                    "a": label_a, "b": label_b,
                    "a_us": round(a_med * 1e6, 2),
                    "b_us": round(b_med * 1e6, 2),
                    "decided": decided,
                    "a_wins": bool(r_med < 1.0)},
             higher_is_better=False)


# -- config 1/headline: BERT-Large pretrain step ----------------------------

def bench_headline(on_tpu):
    import dataclasses

    from apex_tpu.models import bert_large, bert_tiny

    base = bert_large() if on_tpu else bert_tiny()
    seq = 128 if on_tpu else 64
    # b=64 no-remat is the measured winner (r5 sweep under the donating
    # timer: b24 402.6 / b32 425.1 / b48 450.5 / b64 461.2 / b96 449.8
    # samples/s, b32+remat 345.8 — the fixed HBM-bound work amortizes up
    # to b64, then allocator pressure turns the curve over; b>=32
    # no-remat only became viable when the timer stopped holding two
    # train-state copies). Driver mode runs ONLY the winner so the
    # headline always lands inside the budget; re-tune candidates at
    # build time with BENCH_SWEEP=1.
    # every (batch, remat) config now races the optimizer-state modes:
    # "fp32" (legacy) vs "bf16m_castout" (bf16 first moment + fused
    # cast-out consumed by cast_model(precast=...) — the HBM-traffic
    # levers of this round). Driver mode runs both at the winning batch
    # and KEEPS the better one; the loser is printed as a sweep line so
    # a dead end still lands in the record.
    modes = [("fp32", {}),
             ("bf16m_castout", dict(m_dtype=jnp.bfloat16,
                                    emit_compute=True))]
    if not on_tpu:
        configs = [(2, False)]
    elif _SWEEP:
        configs = [(48, False), (64, False), (96, False)]
    else:
        configs = [(64, False)]
    configs = [(b, r, mode) for b, r in configs for mode in modes]
    best = None
    train_step = state = init = None
    metric = ("bert_large_pretrain_step_amp_O2_fused_adam"
              if on_tpu else "bert_tiny_cpu_smoke")
    extra = {}
    for batch, remat, (mode_name, mode_kw) in configs:
        # release the previous config's closures before building the
        # next (the donating timer holds only one live train state)
        train_step = state = init = None
        cfg = dataclasses.replace(base, remat=remat)
        train_step, make_state, (ids, mask) = _bert_step(batch, seq, cfg,
                                                         **mode_kw)

        def body(st, train_step=train_step, ids=ids, mask=mask):
            out = train_step(*st[:-1], ids, mask)
            return out  # (..., loss) — same arity as the state tuple

        def init(make_state=make_state):
            return (*make_state(), jnp.float32(0))

        try:
            dt = timed(body, init, lambda s: s[-1],
                       M=10 if on_tpu else 2, K=5, donate=True)
            # sanity gate on the CONTRACT metric: >3x off the LAST
            # driver-recorded throughput -> measure once more, keep the
            # better run (relay damage only subtracts throughput).
            # prior[-1], not max(prior): this gate asks "did THIS run go
            # off the rails vs the round before it" — the same question
            # vs_baseline answers — while checked() gates raw times
            # against the best round because a damaged recorded value
            # must not poison its reference. One damaged *throughput*
            # round can't poison prior[-1] upward, so latest is right
            # here and the two gates are intentionally different.
            prior = [v for v in _recorded_values(metric) if v]
            if prior and not _SWEEP and on_tpu:
                if not (1 / 3.0 < (batch / dt) / prior[-1] < 3.0):
                    first = batch / dt
                    dt = min(dt, timed(body, init, lambda s: s[-1],
                                       M=10, K=5, donate=True))
                    extra = {"retried": True, "first": round(first, 2)}
        except Exception as e:  # OOM at a candidate config: skip it
            print(json.dumps(
                {"metric": f"headline_b{batch}_remat{remat}_{mode_name}",
                 "error": repr(e)[:160]}), flush=True)
            continue
        sps = batch / dt
        # per-mode line ALWAYS printed (not only under _SWEEP): the
        # state-mode race must leave a record even when a mode loses —
        # that line IS the "measured dead end" evidence for BASELINE.md
        print(json.dumps(
            {"metric": f"headline_b{batch}_remat{remat}_{mode_name}",
             "sweep_samples_per_sec": round(sps, 2),
             "step_ms": round(dt * 1e3, 2)}), flush=True)
        if best is None or sps > best[0]:
            best = (sps, batch, remat, mode_name, dt)
    if best is None:
        raise RuntimeError(
            "every headline config failed (see the error lines above)")
    sps, batch, remat, mode_name, dt = best
    tflops = 6 * BERT_LARGE_PARAMS * batch * seq / dt / 1e12 if on_tpu \
        else 0.0
    extra.update({"batch": batch, "seq": seq, "remat": remat,
                  "state_mode": mode_name,
                  "step_ms": round(dt * 1e3, 2), "tflops": round(tflops, 1)})
    emit(metric, sps, "samples/sec/chip", extra=extra)


# -- compiled-kernel numerics parity ----------------------------------------

def bench_kernel_parity(on_tpu, quiet=False):
    """Compiled-Mosaic vs plain-jnp numerics for every Pallas kernel
    family. CI runs the kernels in interpret mode on the CPU rig (1-core
    host, no chip), so a Mosaic miscompile would pass the whole suite
    and first surface as a bad loss — this config closes that hole at
    driver time by asserting parity ON the chip (round-4 verdict weak
    #7). Emits one pass/fail line; failures name the check. ``quiet``
    (the pollution instrument's replay) skips the emit so the throwaway
    run leaves no metric record."""
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.normalization import (fused_layer_norm_affine,
                                        fused_rms_norm_affine)
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.functional import (
        flash_attention, scaled_masked_softmax,
        scaled_upper_triang_masked_softmax)

    key = jax.random.PRNGKey(0)
    results = {}

    def rel(a, b):
        # PER-LEAF relative error, then max over leaves: a global
        # denominator would let the large loss scalar (O(1e3)) mask
        # garbage in O(1) gradient leaves — the exact failure this
        # parity gate exists to catch
        a = jax.tree.map(lambda x: x.astype(jnp.float32), a)
        b = jax.tree.map(lambda x: x.astype(jnp.float32), b)
        return max(
            float(jnp.max(jnp.abs(x - y)))
            / max(float(jnp.max(jnp.abs(y))), 1e-6)
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))

    def check(name, tol, kernel_fn, ref_fn, *args):
        got = jax.jit(kernel_fn)(*args)
        want = jax.jit(ref_fn)(*args)
        results[name] = (round(rel(got, want), 5), tol)

    # layer norm / rms norm: fwd+bwd at both backward structures (row
    # path h=1024, column-split path h=4096)
    for h in (1024, 4096):
        x = jax.random.normal(key, (256, h), jnp.bfloat16)
        w = jax.random.normal(jax.random.fold_in(key, 1), (h,), jnp.float32)
        b = jax.random.normal(jax.random.fold_in(key, 2), (h,), jnp.float32)
        dy = jax.random.normal(jax.random.fold_in(key, 3), (256, h),
                               jnp.bfloat16)

        def ln_ref(x, w, b, dy, h=h):
            xf = x.astype(jnp.float32)
            mu = jnp.mean(xf, -1, keepdims=True)
            var = jnp.mean((xf - mu) ** 2, -1, keepdims=True)
            y = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * w + b
            return y.astype(x.dtype)

        def ln_kern(x, w, b, dy, h=h):
            return fused_layer_norm_affine(x, w, b, h, 1e-5)

        def wrap(f):
            def g(x, w, b, dy):
                def loss(x, w, b):
                    return jnp.sum(f(x, w, b, dy).astype(jnp.float32)
                                   * dy.astype(jnp.float32))
                l, grads = jax.value_and_grad(loss, (0, 1, 2))(x, w, b)
                return (l, *grads)
            return g

        check(f"ln_h{h}", 3e-2, wrap(ln_kern), wrap(ln_ref), x, w, b, dy)

        def rms_ref(x, w, dy, h=h):
            xf = x.astype(jnp.float32)
            ms = jnp.mean(xf ** 2, -1, keepdims=True)
            return (xf * jax.lax.rsqrt(ms + 1e-5) * w).astype(x.dtype)

        def rms_kern(x, w, dy, h=h):
            return fused_rms_norm_affine(x, w, h, 1e-5)

        def wrap2(f):
            def g(x, w, dy):
                def loss(x, w):
                    return jnp.sum(f(x, w, dy).astype(jnp.float32)
                                   * dy.astype(jnp.float32))
                l, grads = jax.value_and_grad(loss, (0, 1))(x, w)
                return (l, *grads)
            return g

        check(f"rms_h{h}", 3e-2, wrap2(rms_kern), wrap2(rms_ref), x, w, dy)

    # flash attention: causal and padding-masked, fwd + dq/dk/dv, kernel
    # vs the mathematically-identical unfused XLA path
    b_, h_, s_, d_ = 2, 4, 512, 64
    ks = jax.random.split(key, 4)
    q, k, v = (jax.random.normal(kk, (b_, h_, s_, d_), jnp.bfloat16)
               for kk in ks[:3])
    pad_mask = (jnp.arange(s_)[None, :] < s_ - 64).astype(jnp.int32)
    pad_mask = jnp.broadcast_to(pad_mask, (b_, s_))

    def fa(uk, mask, causal):
        def g(q, k, v):
            def loss(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, mask, causal=causal,
                    use_kernel=uk).astype(jnp.float32) ** 2)
            l, grads = jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
            return (l, *grads)
        return g

    check("flash_causal", 5e-2, fa(True, None, True),
          fa(False, None, True), q, k, v)
    check("flash_masked", 5e-2, fa(True, pad_mask, False),
          fa(False, pad_mask, False), q, k, v)

    # dropout parity, kernel vs unfused: both paths derive the keep mask
    # from the same counter hash, so with an identical seed they must
    # agree to the same tolerance as the deterministic checks — this is
    # the compiled-Mosaic guard for the mask-regeneration path (the bwd
    # kernels REBUILD the mask rather than storing it; a compiled-only
    # divergence would silently train on inconsistent fwd/bwd masks)
    def fad(uk):
        def g(q, k, v):
            def loss(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, use_kernel=uk,
                    dropout_rate=0.3,
                    dropout_rng=jax.random.PRNGKey(7),
                ).astype(jnp.float32) ** 2)
            l, grads = jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
            return (l, *grads)
        return g

    check("flash_dropout", 5e-2, fad(True), fad(False), q, k, v)

    # dropout composed with a padding mask and causality: the keep mask
    # and the -inf mask interact in the kernel's tile loop (a fully
    # masked-out row must not be rescaled by 1/keep_prob into NaNs), so
    # the combined branch gets its own compiled parity gate
    def fadm(uk):
        def g(q, k, v):
            def loss(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, pad_mask, causal=True, use_kernel=uk,
                    dropout_rate=0.3,
                    dropout_rng=jax.random.PRNGKey(7),
                ).astype(jnp.float32) ** 2)
            l, grads = jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
            return (l, *grads)
        return g

    check("flash_dropout_masked", 5e-2, fadm(True), fadm(False), q, k, v)

    # VPU-diet pinning: the shipped kernels (exp2 online softmax + bf16
    # p-tiles) vs the SAME kernels traced under the legacy toggles.
    # Catches a compiled-Mosaic divergence between the variants that the
    # unfused reference above can't isolate (both toggles change only
    # kernel-internal arithmetic, so kernel-vs-kernel is the tight
    # comparison; tolerance matches the flash family's)
    fam = _flash_mod()

    def fa_legacy(mask, causal):
        inner = fa(True, mask, causal)

        def g(q, k, v):
            # trace-time context: the toggles are baked in during the
            # trace of this call, before any measurement-side jit cache
            # could alias the shipped variant
            with fam.kernel_variant(exp2=False, p_bf16=False):
                return inner(q, k, v)
        return g

    check("flash_exp2_bf16p_vs_legacy", 5e-2, fa(True, None, True),
          fa_legacy(None, True), q, k, v)

    def fad_legacy():
        inner = fad(True)

        def g(q, k, v):
            with fam.kernel_variant(exp2=False, p_bf16=False):
                return inner(q, k, v)
        return g

    # dropout must be VARIANT-INVARIANT: same seed, same keep mask, so
    # new-vs-legacy with dropout on pins both the arithmetic change and
    # the mask's independence from the toggles in one check
    check("flash_dropout_vs_legacy", 5e-2, fad(True), fad_legacy(),
          q, k, v)

    # fused softmax pair vs jnp
    x4 = jax.random.normal(ks[3], (2, 4, 256, 256), jnp.bfloat16)
    smask = (jax.random.uniform(ks[3], (2, 1, 256, 256)) < 0.2)

    def sm_ref(x4, smask):
        s = x4.astype(jnp.float32) * 0.5
        s = jnp.where(smask, -10000.0, s)
        return jax.nn.softmax(s, -1).astype(x4.dtype)

    check("softmax_masked", 3e-2,
          lambda x4, m: scaled_masked_softmax(x4, m, 0.5), sm_ref,
          x4, smask)

    def sut_ref(x4):
        s = x4.astype(jnp.float32) * 0.5
        tri = jnp.arange(256)[None, :] <= jnp.arange(256)[:, None]
        s = jnp.where(tri[None, None], s, -10000.0)
        return jax.nn.softmax(s, -1).astype(x4.dtype)

    check("softmax_causal", 3e-2,
          lambda x4: scaled_upper_triang_masked_softmax(x4, 0.5),
          sut_ref, x4)

    # fused cross entropy (fwd + dlogits) vs logsumexp reference,
    # including ignored labels
    logits = jax.random.normal(key, (256, 4096), jnp.float32)
    labels = jax.random.randint(jax.random.fold_in(key, 9), (256,), 0, 4096)
    labels = labels.at[::7].set(-1)

    def xent(f):
        def g(logits, labels):
            def loss(logits):
                return jnp.sum(f(logits, labels))
            l, dl = jax.value_and_grad(loss)(logits)
            return (l, dl)
        return g

    def xent_ref(logits, labels):
        lse = jax.scipy.special.logsumexp(logits, -1)
        nll = lse - jnp.take_along_axis(
            logits, jnp.maximum(labels, 0)[:, None], 1)[:, 0]
        return jnp.where(labels >= 0, nll, 0.0)

    check("xentropy", 1e-3, xent(softmax_cross_entropy_loss),
          xent(xent_ref), logits, labels)

    # flat-buffer Pallas optimizer step vs the tree (pure-XLA) step with
    # identical hyperparameters
    nt = 32
    keys2 = jax.random.split(key, nt)
    params = {f"t{i}": jax.random.normal(kk, (64, 128)) for i, kk in
              enumerate(keys2)}
    grads = jax.tree.map(lambda p: p * 1e-3, params)
    o_tree = FusedAdam(lr=1e-3, weight_decay=0.01)
    o_flat = FusedAdam(lr=1e-3, weight_decay=0.01, use_flat_kernel=True)

    def step3(opt):
        st = opt.init(params)
        def g(params, grads):
            p, _ = opt.step(grads, params, st)
            return p
        return g

    check("adam_flat_vs_tree", 1e-5, step3(o_flat), step3(o_tree),
          params, grads)

    # reduced-precision state modes: bf16-m flat kernel vs the bf16-m
    # tree path (same round-to-nearest m store on both sides), and the
    # kernel's fused cast-out vs a plain jnp cast of the tree result
    o_tree_bf = FusedAdam(lr=1e-3, weight_decay=0.01,
                          m_dtype=jnp.bfloat16)
    o_flat_bf = FusedAdam(lr=1e-3, weight_decay=0.01,
                          m_dtype=jnp.bfloat16, use_flat_kernel=True)
    check("adam_bf16m_flat_vs_tree", 1e-5, step3(o_flat_bf),
          step3(o_tree_bf), params, grads)

    o_emit = FusedAdam(lr=1e-3, weight_decay=0.01,
                       emit_compute_params=True, use_flat_kernel=True)
    st_emit = o_emit.init(params)

    def castout_kernel(params, grads):
        _, _, c = o_emit.step(grads, params, st_emit)
        return c

    def castout_ref(params, grads):
        p = step3(o_tree)(params, grads)
        return jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)

    check("adam_castout_vs_jnp_cast", 1e-5, castout_kernel, castout_ref,
          params, grads)

    failures = [n for n, (d, tol) in results.items() if d > tol]
    if quiet:
        return failures
    emit("kernel_parity_compiled", 0.0 if failures else 1.0, "pass",
         extra={"checks": len(results), "failures": failures,
                "rel_diffs": {n: d for n, (d, _) in results.items()},
                "compiled": bool(on_tpu)})


CONFIGS = {
    "layer_norm": bench_layer_norm,
    "opt_adam": functools.partial(bench_one_optimizer, "adam"),
    "opt_lamb": functools.partial(bench_one_optimizer, "lamb"),
    "opt_flat_vs_tree": bench_flat_vs_tree_many_tensors,
    "ddp_bert": bench_ddp_bert,
    "tp_gpt": bench_tp_gpt,
    "flash_attention": bench_flash_attention,
    "kernel_parity": bench_kernel_parity,
    "ab_kernels": bench_ab,
    "headline": bench_headline,
    "gpt_decode": bench_gpt_decode,
    "gpt_spec_natural": bench_gpt_spec_natural,
    "gpt_serving_scenarios": bench_gpt_serving_scenarios,
    "serving_pool_scaling": bench_gpt_serving_pool,
    "serving_multitenant": bench_gpt_serving_multitenant,
}

# Driver execution order (round-4 postmortem). The HEADLINE runs FIRST:
# BENCH_r04 hit the driver's wall-clock cap (rc=124) with the contract
# metric still unmeasured because it ran last. kernel_parity + flash run
# next (cheap, and flash gets measured before any big-model config can
# leave the relay/allocator in a damaged state — the leading theory for
# r4's 27x seq2048 anomaly, which followed two GPT OOMs). The headline
# line is RE-EMITTED at the very end so the driver's parse-the-tail
# convention still lands on the contract metric.
ORDER = ["headline", "gpt_decode", "gpt_spec_natural",
         "gpt_serving_scenarios", "serving_pool_scaling",
         "serving_multitenant",
         "kernel_parity", "flash_attention",
         "ab_kernels", "layer_norm", "opt_adam", "opt_lamb",
         "opt_flat_vs_tree", "ddp_bert", "tp_gpt"]

# Global wall budget (seconds) with per-config caps: the driver must see
# a finished run. Generous-but-bounded; BENCH_BUDGET_S overrides. Cap
# sizing (r5 shakeout, single-compile timer): XLA compiles through the
# relay are the dominant cost and drift 2-3x between runs (the scan'd
# Adam chunk compiled in 390/277/115 s on three consecutive tries), so
# caps are ~2x the observed wall of each config.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "2700"))
CAP_S = {"headline": 600, "kernel_parity": 480, "ddp_bert": 540,
         "tp_gpt": 600, "flash_attention": 540, "ab_kernels": 540,
         "gpt_decode": 420, "gpt_spec_natural": 420,
         "gpt_serving_scenarios": 420, "serving_pool_scaling": 420,
         "serving_multitenant": 420}
DEFAULT_CAP_S = 480


def main():
    global _TRACE_OUT

    from apex_tpu.utils.platform import has_tpu

    if "--trace-out" in sys.argv:
        i = sys.argv.index("--trace-out")
        try:
            _TRACE_OUT = sys.argv[i + 1]
        except IndexError:
            print(json.dumps({"metric": "trace_out",
                              "error": "--trace-out needs a path"}),
                  flush=True)
            return
        del sys.argv[i:i + 2]
    if len(sys.argv) > 1 and sys.argv[1] == "ab":
        # targeted A/B runs: `python bench.py ab [pair ...]` (no pair
        # names = the whole registry). Same code path as the ab_kernels
        # driver config, so interactive and driver numbers are
        # methodology-identical.
        bench_ab(has_tpu(), names=sys.argv[2:] or None)
        return
    if len(sys.argv) > 1 and sys.argv[1] in CONFIGS:
        try:
            CONFIGS[sys.argv[1]](has_tpu())
        except Exception as e:
            print(json.dumps({"metric": sys.argv[1],
                              "error": repr(e)[:200]}), flush=True)
        return
    # Parent mode: one subprocess per config. BERT-Large fp32 params +
    # Adam state ~ 4 GB per config and the TPU allocator does not always
    # return freed pages promptly through the relay -- process isolation
    # guarantees each config starts with an empty HBM.
    import subprocess
    deadline = time.time() + BUDGET_S
    headline_line = None
    # BENCH_ONLY="headline,layer_norm" filters the run (test rig /
    # targeted re-measures); order is still ORDER's.
    only = [s.strip() for s in os.environ.get("BENCH_ONLY", "").split(",")
            if s.strip()]
    for name in only:
        if name not in CONFIGS:
            print(json.dumps({"metric": name,
                              "error": "unknown BENCH_ONLY config"}),
                  flush=True)
    for name in ORDER:
        if only and name not in only:
            continue
        remaining = deadline - time.time()
        if remaining < 45:
            print(json.dumps({"metric": name,
                              "skipped": "global budget exhausted"}),
                  flush=True)
            continue
        cap = min(CAP_S.get(name, DEFAULT_CAP_S), remaining)
        try:
            argv = [sys.executable, os.path.abspath(__file__), name]
            if _TRACE_OUT:
                argv += ["--trace-out", _TRACE_OUT]
            r = subprocess.run(
                argv, capture_output=True, text=True, timeout=cap)
        except subprocess.TimeoutExpired as e:
            out = e.stdout or b""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            for line in out.splitlines():
                if line.startswith("{"):
                    print(line, flush=True)
                    if '"bert_large_pretrain' in line \
                            or '"bert_tiny_cpu_smoke' in line:
                        headline_line = line
            print(json.dumps({"metric": name,
                              "error": f"config cap {cap:.0f}s hit"}),
                  flush=True)
            continue
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                print(line, flush=True)
                if '"bert_large_pretrain' in line \
                        or '"bert_tiny_cpu_smoke' in line:
                    headline_line = line
        if r.returncode != 0 and not any(
                ln.startswith("{") for ln in r.stdout.splitlines()):
            print(json.dumps({"metric": name,
                              "error": (r.stderr or "")[-200:]}), flush=True)
    if headline_line:  # the tail-parsed line must be the contract metric
        print(headline_line, flush=True)


if __name__ == "__main__":
    main()

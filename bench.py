#!/usr/bin/env python
"""Headline benchmark: BERT-Large pretrain step (amp O2 + FusedAdam +
FusedLayerNorm), samples/sec/chip — the north-star metric of BASELINE.json.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is measured/previous-round (BENCH_r*.json) when available,
else null (the reference publishes no numbers — BASELINE.md).
"""

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp


def main():
    from apex_tpu import amp
    from apex_tpu.models import apply_bert, bert_large, bert_tiny, init_bert, mlm_loss
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.utils.platform import has_tpu

    on_tpu = has_tpu()
    cfg = bert_large() if on_tpu else bert_tiny()
    batch, seq = (16, 128) if on_tpu else (2, 64)
    steps = 10 if on_tpu else 2

    h = amp.initialize(opt_level="O2", loss_scale="dynamic")
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-4, weight_decay=0.01)
    opt_state = opt.init(params)
    scaler_state = h.init_state()

    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    mask = jnp.ones((batch, seq), jnp.int32)

    def loss_fn(p):
        out = apply_bert(p, cfg, ids, mask)
        return mlm_loss(out["mlm_logits"], ids, mask)

    @jax.jit
    def train_step(master, opt_state, scaler_state):
        p = h.cast_model(master)
        loss, grads, found_inf, scaler_state = h.value_and_grad(loss_fn)(
            p, scaler_state)
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        return master, opt_state, scaler_state, loss

    # compile + warmup
    params, opt_state, scaler_state, loss = train_step(
        params, opt_state, scaler_state)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, scaler_state, loss = train_step(
            params, opt_state, scaler_state)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = steps * batch / dt
    metric = ("bert_large_pretrain_step_amp_O2_fused_adam"
              if on_tpu else "bert_tiny_cpu_smoke")
    prev = None
    runs = sorted(glob.glob(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r*.json")))
    if runs:
        try:
            rec = json.load(open(runs[-1]))
            # only compare like with like (a CPU smoke run must not be
            # ratioed against a TPU number)
            if rec.get("metric") == metric:
                prev = rec.get("value")
        except Exception:
            prev = None
    vs = (samples_per_sec / prev) if prev else None

    print(json.dumps({
        "metric": metric,
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs, 3) if vs else None,
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""BASELINE benchmark suite (BASELINE.md / BASELINE.json).

Prints one JSON line per config, the NORTH-STAR metric LAST (the driver
records the tail of stdout):

  1. FusedLayerNorm fwd+bwd microbench, hidden 1024 / 4096
  2. FusedAdam / FusedLAMB optimizer step on the BERT-Large param set
  3. DDP BERT-Large train step over all local devices (dp = n_devices)
  4. Tensor-parallel GPT train step (tp = n_devices)
  5. BERT-Large pretrain step, amp O2 + FusedAdam + FusedLayerNorm
     (samples/sec/chip — the headline)

Timing methodology (see axon-relay pitfall): ``jax.block_until_ready``
does not reliably synchronize through the relay, so every measured chunk
ends in a ``float()`` fetch of a value data-dependent on the whole chunk;
chunks of M chained steps amortize the fetch round-trip; the reported
number is the median over K chunks. ``vs_baseline`` compares against the
matching metric in the latest driver-written ``BENCH_r*.json`` (nested
under ``"parsed"``) when present, else null (the reference publishes no
numbers — BASELINE.md).
"""

import functools
import glob
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

BERT_LARGE_PARAMS = 336e6  # ≈ param count incl. embeddings


def _recorded_values(metric):
    """All recorded values for `metric` from driver BENCH_r*.json files
    (the driver nests the printed line under "parsed"), oldest first."""
    vals = []
    runs = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json")))
    for path in runs:
        try:
            rec = json.load(open(path))
        except Exception:
            continue
        parsed = rec.get("parsed") or {}
        candidates = [parsed] if isinstance(parsed, dict) else list(parsed)
        for c in candidates:
            if isinstance(c, dict) and c.get("metric") == metric \
                    and c.get("value") is not None:
                vals.append(c["value"])
    return vals


def emit(metric, value, unit, extra=None, higher_is_better=True):
    """vs_baseline compares to the LATEST recorded round; vs_best to the
    best round EVER, so a regression-after-a-regression can't report >1
    (round-3 verdict weak #8). Both >1 = this run is better."""
    # drop zeros: a recorded 0 (failed round, or rounded-to-0.0 tiny
    # value) would be a zero denominator in the ratios below
    prior = [v for v in _recorded_values(metric) if v]
    rec = {"metric": metric, "value": round(value, 2), "unit": unit,
           "vs_baseline": None}
    if prior:
        prev = prior[-1]
        best = max(prior) if higher_is_better else min(prior)
        ratio = (lambda new, old: new / old) if higher_is_better \
            else (lambda new, old: old / new)
        rec["vs_baseline"] = round(ratio(value, prev), 3)
        rec["vs_best"] = round(ratio(value, best), 3)
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def timed(body, init_state, fetch, M, K=4):
    """Median seconds per iteration of ``body`` (state -> state, a pytree
    step function), measured by DIFFERENCING two scan-chunk lengths.

    The axon relay imposes a ~100 ms fixed cost on every dispatch+fetch
    cycle regardless of the work inside (measured: 50 fused multiplies of
    a 16 MB array and a single one both take ~100 ms end to end), and
    ``block_until_ready`` is not a reliable sync, so: run the body M and
    5M times inside single jitted ``lax.scan`` chunks, end each in a
    ``float()`` fetch of a chunk-dependent scalar, and report
    (t(5M) - t(M)) / 4M — the fixed overhead cancels exactly. Sanity
    anchor: this methodology reproduces the v5e bf16 peak (197 TFLOP/s)
    on a 4096^3 matmul chain."""
    M1, M2 = M, 5 * M

    def chunk_fn(length):
        @jax.jit
        def chunk(state):
            def f(s, _):
                return body(s), ()
            s, _ = jax.lax.scan(f, state, None, length=length)
            return s
        return chunk

    c1, c2 = chunk_fn(M1), chunk_fn(M2)

    def t_of(chunk):
        state = chunk(init_state)
        float(fetch(state))  # compile + sync
        ts = []
        for _ in range(K):
            t0 = time.perf_counter()
            state = chunk(init_state)
            float(fetch(state))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    return max(t_of(c2) - t_of(c1), 1e-9) / (M2 - M1)


# -- config 2: LN microbench ------------------------------------------------

def bench_layer_norm(on_tpu):
    from apex_tpu.normalization import fused_layer_norm_affine

    rows = 8192 if on_tpu else 64
    for h in (1024, 4096):
        x = jax.random.normal(jax.random.PRNGKey(0), (rows, h), jnp.bfloat16)
        # |w| < 1 makes the dy -> dx chain strictly contracting (LN's
        # input-grad is a projection scaled by w·rstd), so the raw dx
        # can feed the next iteration's dy with NO normalization pass:
        # the body moves exactly the 5 streams the GB/s model counts.
        # Values decay toward zero; TPU arithmetic is value-independent,
        # so timing is unaffected and the chain stays data-dependent.
        w = jnp.full((h,), 0.9, jnp.float32)
        b = jnp.zeros((h,), jnp.float32)
        dy0 = jax.random.normal(jax.random.PRNGKey(1), (rows, h),
                                jnp.bfloat16)

        def body(dy, h=h):
            # Training-shaped workload (changed r4): fwd + bwd with an
            # EXTERNAL cotangent dy, as an upstream layer supplies.
            # Rounds 1-3 measured grad(sum(LN(x)^2)) — a self-cotangent
            # body whose dy = 2y fuses away; numbers are not comparable
            # across that change.
            return jax.grad(
                lambda x: jnp.sum(
                    fused_layer_norm_affine(x, w, b, h, 1e-5).astype(
                        jnp.float32) * dy.astype(jnp.float32)))(x)

        # M sized so the 4M-iteration delta is far above the axon
        # relay's ~±20 ms dispatch noise
        dt = timed(body, dy0, lambda s: jnp.sum(s.astype(jnp.float32)),
                   M=400 if on_tpu else 2)
        # bytes: read x (fwd) + read x,dy (bwd) + write y, dx ~ 5 * 2B
        gbps = 5 * rows * h * 2 / dt / 1e9
        emit(f"fused_layer_norm_fwdbwd_h{h}", dt * 1e6, "us/iter",
             extra={"rows": rows, "GBps": round(gbps, 1)},
             higher_is_better=False)


# -- config 3: optimizer step on BERT-Large param set -----------------------

def _make_optimizer(which):
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    return {
        "adam": lambda: FusedAdam(lr=1e-4, weight_decay=0.01),
        "lamb": lambda: FusedLAMB(lr=1e-3, weight_decay=0.01),
    }[which]()


def bench_one_optimizer(which, on_tpu):
    """One optimizer per subprocess: BERT-Large fp32 state doesn't fit
    twice in HBM (measured ResourceExhausted when chained in-process)."""
    from apex_tpu.models import bert_large, bert_tiny, init_bert

    cfg = bert_large() if on_tpu else bert_tiny()
    params = init_bert(jax.random.PRNGKey(0), cfg)
    grads = jax.tree.map(lambda p: jnp.full_like(p, 1e-4), params)
    opt = _make_optimizer(which)
    opt_state = opt.init(params)

    def body(state):
        p, s = state
        return opt.step(grads, p, s)

    dt = timed(body, (params, opt_state),
               lambda s: jnp.sum(s[0]["pooler"]["bias"]),
               M=10 if on_tpu else 2)
    emit(f"fused_{which}_step_bert_large_params", dt * 1e3, "ms/step",
         higher_is_better=False)


def bench_flat_vs_tree_many_tensors(on_tpu):
    """The flat path's actual claim (fused_adam docstring): it pays off
    when per-leaf overhead dominates — a 1024-small-tensor param set
    (the BERT-Large set is 400 LARGE tensors, where the tree path's XLA
    fusion already wins and the flat round-trip can't fit in HBM)."""
    from apex_tpu.optimizers import FusedAdam

    n = 1024 if on_tpu else 32
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    params = {f"t{i}": jax.random.normal(k, (64, 128)) for i, k in
              enumerate(keys)}
    grads = jax.tree.map(lambda p: jnp.full_like(p, 1e-4), params)
    for name, opt in (
            ("tree", FusedAdam(lr=1e-4, weight_decay=0.01)),
            ("flat", FusedAdam(lr=1e-4, weight_decay=0.01,
                               use_flat_kernel=True))):
        opt_state = opt.init(params)

        def body(state, opt=opt):
            p, s = state
            return opt.step(grads, p, s)

        dt = timed(body, (params, opt_state),
                   lambda s: jnp.sum(s[0]["t0"]), M=20 if on_tpu else 2)
        emit(f"fused_adam_{name}_{n}_small_tensors", dt * 1e3, "ms/step",
             higher_is_better=False)


# -- shared BERT train-step builder ----------------------------------------

def _bert_step(batch, seq, cfg):
    from apex_tpu import amp
    from apex_tpu.models import apply_bert, init_bert, mlm_loss
    from apex_tpu.optimizers import FusedAdam

    h = amp.initialize(opt_level="O2", loss_scale="dynamic")
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = FusedAdam(lr=1e-4, weight_decay=0.01)
    opt_state = opt.init(params)
    scaler_state = h.init_state()
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                             cfg.vocab_size)
    mask = jnp.ones((batch, seq), jnp.int32)

    def train_step(master, opt_state, scaler_state, ids, mask):
        def loss_fn(p):
            out = apply_bert(p, cfg, ids, mask)
            return mlm_loss(out["mlm_logits"], ids, mask)

        p = h.cast_model(master)
        loss, grads, found_inf, scaler_state = h.value_and_grad(loss_fn)(
            p, scaler_state)
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        return master, opt_state, scaler_state, loss

    return train_step, (params, opt_state, scaler_state), (ids, mask)


# -- config 4: DDP BERT over all local devices ------------------------------

def bench_ddp_bert(on_tpu):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from apex_tpu.models import bert_large, bert_tiny

    n = jax.device_count()
    cfg = bert_large() if on_tpu else bert_tiny()
    # b=24/chip: fits without remat and amortizes the HBM-bound fixed
    # work (optimizer + master-weight traffic) — the measured headline
    # winner (b=32 ResourceExhausted without remat; see bench_headline)
    per_dev_batch, seq = (24, 128) if on_tpu else (2, 64)
    batch = per_dev_batch * n
    mesh = Mesh(jax.devices(), ("data",))
    train_step, state, (ids, mask) = _bert_step(batch, seq, cfg)
    # GSPMD DP: batch sharded over the data axis, params replicated —
    # jit propagates the sharding; XLA inserts the grad all-reduce.
    data_sharding = NamedSharding(mesh, P("data", None))
    ids = jax.device_put(ids, data_sharding)
    mask = jax.device_put(mask, data_sharding)

    def body(st):
        m, o, sc, _ = train_step(st[0], st[1], st[2], ids, mask)
        return (m, o, sc, _)

    init = (*state, jnp.float32(0))
    dt = timed(body, init, lambda s: s[3], M=10 if on_tpu else 2)
    sps = batch / dt / n
    emit(f"bert_ddp_dp{n}_step", sps, "samples/sec/chip",
         extra={"per_device_batch": per_dev_batch, "devices": n,
                "step_ms": round(dt * 1e3, 2)})


# -- config 5 (from round 3): TP GPT ---------------------------------------

def bench_tp_gpt(on_tpu):
    try:
        from apex_tpu.models.gpt import gpt_tp_bench
    except ImportError:
        return  # GPT lands later this round
    n = jax.device_count()
    # sweep batch/remat like the BERT headline: the fixed memory-bound
    # work (optimizer on ~350M fp32 params) amortizes over the batch
    configs = [(8, False), (16, False), (16, True)] if on_tpu \
        else [(None, False)]
    best = None
    body = init = fetch = None
    for batch, remat in configs:
        # drop the previous config's sharded train state (params + Adam
        # m/v, ~4 GB fp32 for gpt_medium) BEFORE allocating the next, or
        # the doubled residency turns later configs into spurious OOMs
        body = init = fetch = None
        try:
            body, init, fetch, b = gpt_tp_bench(on_tpu, n, batch=batch,
                                                remat=remat)
            dt = timed(body, init, fetch, M=5 if on_tpu else 2)
        except Exception as e:
            print(json.dumps({"metric": f"gpt_b{batch}_remat{remat}",
                              "error": repr(e)[:160]}), flush=True)
            continue
        if best is None or b / dt > best[0]:
            best = (b / dt, b, remat, dt)
    if best is None:
        raise RuntimeError("every GPT bench config failed (see above)")
    sps, b, remat, dt = best
    emit(f"gpt_tp{n}_step", sps, "samples/sec",
         extra={"devices": n, "batch": b, "remat": remat,
                "step_ms": round(dt * 1e3, 2)})


# -- flash-attention microbench: kernel vs unfused at long seq --------------

def bench_flash_attention(on_tpu):
    """fwd+bwd at seq 2048 (b·h·s·d sized for one chip): the Pallas
    kernel vs XLA's materialized-scores path — the dispatch-crossover
    evidence (flash_attention.py picks the kernel above seq 256)."""
    from apex_tpu.transformer.functional import flash_attention

    b, h, s, d = (4, 16, 2048, 64) if on_tpu else (1, 2, 256, 16)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
               for kk in ks)

    for name, use_kernel in (("kernel", True), ("unfused", False)):
        def body(q, uk=use_kernel):
            g = jax.grad(lambda q: jnp.sum(flash_attention(
                q, k, v, causal=True, use_kernel=uk).astype(jnp.float32)
                ** 2))(q)
            return (g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-6)).astype(
                q.dtype)

        dt = timed(body, q, lambda x: jnp.sum(x.astype(jnp.float32)),
                   M=10 if on_tpu else 2)
        # causal attention FLOPs: ~2·(QK + PV + bwd≈2.5x) over s²/2
        flops = 2 * 3.5 * b * h * s * s * d
        emit(f"flash_attention_{name}_seq{s}_fwdbwd", dt * 1e3, "ms/iter",
             extra={"tflops": round(flops / dt / 1e12, 1)},
             higher_is_better=False)

    # long-seq causal line (kernel only: materialized scores at 4096 would
    # need a 4.3 GB fp32 tensor; b halved to keep the working set fair)
    b2, s2 = (2, 4096) if on_tpu else (1, 512)
    q2, k2, v2 = (jax.random.normal(kk, (b2, h, s2, d), jnp.bfloat16)
                  for kk in ks)

    def body2(q2):
        g = jax.grad(lambda q2: jnp.sum(flash_attention(
            q2, k2, v2, causal=True, use_kernel=True).astype(jnp.float32)
            ** 2))(q2)
        return (g / jnp.maximum(jnp.max(jnp.abs(g)), 1e-6)).astype(q2.dtype)

    dt = timed(body2, q2, lambda x: jnp.sum(x.astype(jnp.float32)),
               M=10 if on_tpu else 2)
    flops = 2 * 3.5 * b2 * h * s2 * s2 * d
    emit(f"flash_attention_kernel_seq{s2}_fwdbwd", dt * 1e3, "ms/iter",
         extra={"tflops": round(flops / dt / 1e12, 1)},
         higher_is_better=False)


# -- config 1/headline: BERT-Large pretrain step ----------------------------

def bench_headline(on_tpu):
    import dataclasses

    from apex_tpu.models import bert_large, bert_tiny

    base = bert_large() if on_tpu else bert_tiny()
    seq = 128 if on_tpu else 64
    # b=16 was the assumed no-remat HBM ceiling (b=32 OOMs); b=24 fits
    # without remat and amortizes the ~17 ms/step of memory-bound fixed
    # work (optimizer + master-weight traffic — see BASELINE.md roofline)
    # over 1.5x the samples; remat unlocks b=32 at ~33% fwd recompute.
    # Measure all three, report the winner.
    configs = [(16, False), (24, False), (32, True)] if on_tpu \
        else [(2, False)]
    best = None
    train_step = state = init = None
    for batch, remat in configs:
        # release the previous config's train state before allocating
        # the next (see bench_tp_gpt)
        train_step = state = init = None
        cfg = dataclasses.replace(base, remat=remat)
        train_step, state, (ids, mask) = _bert_step(batch, seq, cfg)

        def body(st, train_step=train_step, ids=ids, mask=mask):
            m, o, sc, loss = train_step(st[0], st[1], st[2], ids, mask)
            return (m, o, sc, loss)

        init = (*state, jnp.float32(0))
        try:
            dt = timed(body, init, lambda s: s[3], M=10 if on_tpu else 2,
                       K=5)
        except Exception as e:  # OOM at a candidate config: skip it
            print(json.dumps({"metric": f"headline_b{batch}_remat{remat}",
                              "error": repr(e)[:160]}), flush=True)
            continue
        sps = batch / dt
        if best is None or sps > best[0]:
            best = (sps, batch, remat, dt)
    if best is None:
        raise RuntimeError(
            "every headline config failed (see the error lines above)")
    sps, batch, remat, dt = best
    tflops = 6 * BERT_LARGE_PARAMS * batch * seq / dt / 1e12 if on_tpu \
        else 0.0
    metric = ("bert_large_pretrain_step_amp_O2_fused_adam"
              if on_tpu else "bert_tiny_cpu_smoke")
    emit(metric, sps, "samples/sec/chip",
         extra={"batch": batch, "seq": seq, "remat": remat,
                "step_ms": round(dt * 1e3, 2),
                "tflops": round(tflops, 1)})


CONFIGS = {
    "layer_norm": bench_layer_norm,
    "opt_adam": functools.partial(bench_one_optimizer, "adam"),
    "opt_lamb": functools.partial(bench_one_optimizer, "lamb"),
    "opt_flat_vs_tree": bench_flat_vs_tree_many_tensors,
    "ddp_bert": bench_ddp_bert,
    "tp_gpt": bench_tp_gpt,
    "flash_attention": bench_flash_attention,
    "headline": bench_headline,
}


def main():
    from apex_tpu.utils.platform import has_tpu

    if len(sys.argv) > 1 and sys.argv[1] in CONFIGS:
        try:
            CONFIGS[sys.argv[1]](has_tpu())
        except Exception as e:
            print(json.dumps({"metric": sys.argv[1],
                              "error": repr(e)[:200]}), flush=True)
        return
    # Parent mode: one subprocess per config. BERT-Large fp32 params +
    # Adam state ~ 4 GB per config and the TPU allocator does not always
    # return freed pages promptly through the relay -- process isolation
    # guarantees each config starts with an empty HBM.
    import subprocess
    for name in CONFIGS:
        r = subprocess.run([sys.executable, os.path.abspath(__file__), name],
                           capture_output=True, text=True, timeout=1800)
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                print(line, flush=True)
        if r.returncode != 0 and not any(
                ln.startswith("{") for ln in r.stdout.splitlines()):
            print(json.dumps({"metric": name,
                              "error": (r.stderr or "")[-200:]}), flush=True)


if __name__ == "__main__":
    main()

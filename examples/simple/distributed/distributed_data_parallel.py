#!/usr/bin/env python
"""Minimal DDP example — TPU analogue of the reference acceptance script
``examples/simple/distributed/distributed_data_parallel.py`` (a linear
model trained under ``apex.parallel.DistributedDataParallel`` +
``amp.initialize``, launched with ``torch.distributed.launch``).

TPU translation: data parallelism is a mesh axis, not processes — the
script runs single-controller over however many local devices exist
(``--dp``, default all; under the test rig that is the 8-virtual-device
CPU world) and scales to multi-host unchanged when launched via
``python -m apex_tpu.parallel.multiproc`` (jax.distributed rendezvous).
The DDP wrapper contributes exactly what the reference's does: grad
averaging over the data group and initial param broadcast.

Run: python examples/simple/distributed/distributed_data_parallel.py
"""

import argparse
import sys

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

from apex_tpu import amp  # noqa: E402
from apex_tpu.optimizers import FusedSGD  # noqa: E402
from apex_tpu.parallel import DistributedDataParallel  # noqa: E402
from apex_tpu.transformer import parallel_state as ps  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel degree (0: all local devices)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("-b", "--batch-size", type=int, default=64,
                   help="GLOBAL batch size")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()
    dp = args.dp or jax.device_count()
    mesh = ps.initialize_model_parallel(devices=jax.devices()[:dp])
    ddp = DistributedDataParallel()
    h = amp.initialize(opt_level=args.opt_level, loss_scale="dynamic")

    # the reference's toy model: 4096 -> 2048 -> 16 with two linears
    k1, k2, kd = jax.random.split(jax.random.PRNGKey(args.seed), 3)
    params = {
        "fc1": {"w": jax.random.normal(k1, (4096, 2048)) * 0.01,
                "b": jnp.zeros((2048,))},
        "fc2": {"w": jax.random.normal(k2, (2048, 16)) * 0.01,
                "b": jnp.zeros((16,))},
    }
    opt = FusedSGD(lr=args.lr)
    opt_state = opt.init(params)
    scaler_state = h.init_state()

    def loss_fn(p, x, y):
        h1 = jax.nn.relu(x @ p["fc1"]["w"] + p["fc1"]["b"])
        out = h1 @ p["fc2"]["w"] + p["fc2"]["b"]
        return jnp.mean((out.astype(jnp.float32) - y) ** 2)

    def train_step(master, opt_state, scaler_state, x, y):
        # rank-0 params everywhere first (the DDP constructor broadcast)
        master = ddp.broadcast_params(master)
        p = h.cast_model(master)
        loss, grads, found_inf, scaler_state = h.value_and_grad(
            lambda p: loss_fn(p, h.cast_input(x), y))(p, scaler_state)
        grads = ddp.allreduce_grads(grads)   # the DDP hook: mean over dp
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        loss = jax.lax.pmean(loss, ps.DATA_AXIS)
        return master, opt_state, scaler_state, loss

    step = jax.jit(ps.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), P(ps.DATA_AXIS), P(ps.DATA_AXIS)),
        out_specs=(P(), P(), P(), P())))

    for i in range(args.steps):
        k = jax.random.PRNGKey(100 + i)
        x = jax.random.normal(k, (args.batch_size, 4096))
        y = jax.random.normal(k, (args.batch_size, 16))
        params, opt_state, scaler_state, loss = step(
            params, opt_state, scaler_state, x, y)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  dp {dp}  loss {float(loss):.6f}",
                  flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()

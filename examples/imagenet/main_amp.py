#!/usr/bin/env python
"""ImageNet-style training CLI — TPU equivalent of the reference acceptance
test ``examples/imagenet/main_amp.py`` (argparse flags ``--opt-level``,
``--keep-batchnorm-fp32``, ``--loss-scale``, ``-b``, ``--lr`` … preserved).

Differences from the reference, by design:
- data: synthetic (or NPZ folder) — no torchvision dependency on TPU;
- distributed: ``--dp`` shards the batch over the mesh ``data`` axis with a
  gradient psum (the DDP-wrapper path) instead of NCCL process groups;
- the training step is ONE jitted function (fwd+bwd+optimizer), so AMP,
  FusedSGD and the collectives all fuse into a single XLA program.

Run: python examples/imagenet/main_amp.py --steps 30 -b 64 --opt-level O2
"""

import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

# Honor the test rig's platform override BEFORE any device use (plain
# JAX_PLATFORMS is latched away by sitecustomize on this class of host;
# see apply_test_platform_override).
from apex_tpu.utils.platform import apply_test_platform_override  # noqa: E402
apply_test_platform_override()

from apex_tpu import amp  # noqa: E402
from apex_tpu.models import apply_resnet, cross_entropy_loss, init_resnet  # noqa: E402
from apex_tpu.optimizers import FusedSGD  # noqa: E402
from apex_tpu.utils.checkpoint import (  # noqa: E402
    load_checkpoint, save_checkpoint,
)
from apex_tpu.utils.metrics import AverageMeter, Throughput  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description="TPU imagenet example")
    p.add_argument("--arch", "-a", default="resnet50",
                   choices=["resnet10", "resnet18", "resnet34", "resnet50"])
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--opt-level", default="O0",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--seed", type=int, default=0)
    # checkpoint/resume (ref: main_amp.py --resume loading model+optimizer
    # +amp.state_dict; here one atomic file holds the whole train state)
    p.add_argument("--checkpoint", default=None,
                   help="path to write checkpoints to")
    p.add_argument("--save-freq", type=int, default=0,
                   help="save every N steps (0: only at the end)")
    p.add_argument("--resume", default=None,
                   help="checkpoint path to resume from")
    return p.parse_args()


def main():
    args = parse_args()
    depth = int(args.arch.replace("resnet", ""))
    loss_scale = args.loss_scale
    if loss_scale not in (None, "dynamic"):
        loss_scale = float(loss_scale)
    kbn = args.keep_batchnorm_fp32
    if isinstance(kbn, str):
        kbn = kbn.lower() in ("1", "true", "yes")

    h = amp.initialize(opt_level=args.opt_level, loss_scale=loss_scale,
                       keep_batchnorm_fp32=kbn)
    key = jax.random.PRNGKey(args.seed)
    params, bn_stats = init_resnet(key, depth, args.num_classes)
    opt = FusedSGD(lr=args.lr, momentum=args.momentum,
                   weight_decay=args.weight_decay)
    opt_state = opt.init(params)
    scaler_state = h.init_state()
    start_step = 0
    if args.resume:
        ck = load_checkpoint(args.resume)
        params, bn_stats = ck["params"], ck["bn_stats"]
        opt_state = jax.tree.map(lambda ref, a: jnp.asarray(a),
                                 opt_state, ck["opt_state"])
        scaler_state = jax.tree.map(lambda ref, a: jnp.asarray(a),
                                    scaler_state, ck["scaler_state"])
        start_step = int(ck["step"]) + 1
        print(f"resumed from {args.resume} at step {start_step}",
              flush=True)

    def save(step):
        if not args.checkpoint:
            return
        save_checkpoint(args.checkpoint, {
            "step": step, "params": params, "bn_stats": bn_stats,
            "opt_state": opt_state, "scaler_state": scaler_state})

    def loss_fn(p, stats, images, labels):
        logits, new_stats = apply_resnet(p, stats, images, depth, train=True)
        return cross_entropy_loss(logits, labels), new_stats

    # donate the threaded state: master weights + optimizer moments are
    # the big buffers, and without donation XLA keeps input AND output
    # copies live across the step (2x peak state memory for nothing)
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def train_step(master, bn_stats, opt_state, scaler_state, images, labels):
        p = h.cast_model(master)
        images = h.cast_input(images)
        (loss, new_stats), grads, found_inf, scaler_state = h.value_and_grad(
            lambda p: loss_fn(p, bn_stats, images, labels), has_aux=True)(
                p, scaler_state)
        master, opt_state = opt.step(grads, master, opt_state,
                                     found_inf=found_inf)
        # skipped steps keep the old batch stats too
        new_stats = amp.apply_if_finite(new_stats, bn_stats, found_inf)
        return master, new_stats, opt_state, scaler_state, loss

    # synthetic data (deterministic per-step)
    def batch(i):
        k = jax.random.PRNGKey(1000 + i)
        images = jax.random.normal(
            k, (args.batch_size, args.image_size, args.image_size, 3),
            jnp.float32)
        labels = jax.random.randint(k, (args.batch_size,), 0,
                                    args.num_classes)
        return images, labels

    losses = AverageMeter("Loss", ":.4e")
    speed = Throughput()
    if start_step >= args.steps:
        print(f"nothing to do: resumed step {start_step} >= --steps "
              f"{args.steps}")
        return
    for i in range(start_step, args.steps):
        images, labels = batch(i)
        params, bn_stats, opt_state, scaler_state, loss = train_step(
            params, bn_stats, opt_state, scaler_state, images, labels)
        if i == start_step:
            jax.block_until_ready(loss)
            speed.start()
            t0 = time.perf_counter()
        else:
            speed.tick(args.batch_size)
        if i % args.print_freq == 0 or i == args.steps - 1:
            losses.update(float(loss))
            print(f"step {i:4d}  loss {losses.val:.6f}  "
                  f"speed {speed.per_sec:8.1f} img/s", flush=True)
        if args.save_freq and (i + 1) % args.save_freq == 0:
            save(i)
    jax.block_until_ready(loss)
    save(args.steps - 1)
    dt = time.perf_counter() - t0
    done = args.steps - start_step
    n = (done - 1) * args.batch_size
    print(f"FINAL speed {n / max(dt, 1e-9):.1f} img/s  "
          f"step_time {1000 * dt / max(done - 1, 1):.2f} ms")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Megatron-style GPT pretraining CLI — the full parallel stack in one
script (analogue of the reference's ``tests/L0/run_transformer`` pretrain
drivers built on ``apex/transformer/testing``).

Composes: Megatron flag parsing (``transformer.testing.arguments``) →
global mesh (dp × tp × pp) → tensor-parallel GPT through the collective
1F1B schedule → DDP grad mean → FusedAdam, or ZeRO
(``DistributedFusedAdam``) when ``--use-distributed-optimizer`` is set
(grads reduce-scatter over data instead of averaging; optimizer state is
1/dp per device).

Synthetic data; run on the CPU test rig with e.g.::

    python examples/gpt/pretrain_gpt.py --tensor-model-parallel-size 2 \\
        --pipeline-model-parallel-size 2 --num-layers 4 --steps 10
"""

import sys

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

from apex_tpu.contrib.optimizers import DistributedFusedAdam  # noqa: E402
from apex_tpu.models.gpt import (  # noqa: E402
    GPTConfig, GPTModel, accumulate_tied_word_grads, gpt_pipeline_model,
    gpt_pipeline_partition_specs, gpt_to_pipeline_params, init_gpt,
)
from apex_tpu.optimizers import FusedAdam  # noqa: E402
from apex_tpu.transformer import parallel_state as ps  # noqa: E402
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: E402
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.testing import arguments  # noqa: E402


def extra_flags(p):
    g = p.add_argument_group("pretrain")
    g.add_argument("--steps", type=int, default=10)
    g.add_argument("--use-distributed-optimizer", action="store_true")
    g.add_argument("--gradient-accumulation-fusion", action="store_true",
                   help="per-layer fp32 wgrad emission in the TP linears "
                        "(Megatron --gradient-accumulation-fusion)")
    g.add_argument("--seed", type=int, default=0)
    return p


def main():
    ns = arguments.parse_args(extra_args_provider=extra_flags)
    tp_sz, pp = ns.tensor_model_parallel_size, \
        ns.pipeline_model_parallel_size
    mesh = arguments.initialize_from_args(ns)
    dp = ps.get_data_parallel_world_size()
    print(f"mesh: dp={dp} tp={tp_sz} pp={pp}", flush=True)

    if ns.context_parallel_size > 1:
        raise SystemExit(
            "this script does not drive context parallelism — use "
            "transformer.context_parallel.ring_attention directly")
    cfg = GPTConfig(
        vocab_size=ns.padded_vocab_size, hidden_size=ns.hidden_size,
        num_layers=ns.num_layers, num_heads=ns.num_attention_heads,
        ffn_hidden_size=4 * ns.hidden_size,
        max_position_embeddings=ns.max_position_embeddings,
        sequence_parallel=ns.sequence_parallel,
        gradient_accumulation_fusion=ns.gradient_accumulation_fusion)
    vpp = ns.virtual_pipeline_model_parallel_size
    model = GPTModel(cfg, tp_size=tp_sz)
    params = init_gpt(jax.random.PRNGKey(ns.seed), cfg)
    pipe_params = gpt_to_pipeline_params(params, cfg, pp, vpp)
    pipe_model = gpt_pipeline_model(model)
    pspecs = gpt_pipeline_partition_specs(cfg, vpp)

    if ns.use_distributed_optimizer:
        if tp_sz > 1 or pp > 1:
            raise SystemExit(
                "--use-distributed-optimizer composes with pure data "
                "parallelism (the reference's DistributedFusedAdam is "
                "likewise the MLPerf DDP-BERT tool): the ZeRO flat "
                "layout is built from the full param tree, which inside "
                "a tp/pp mesh no longer matches the rank-local shapes. "
                "Drop --tensor/pipeline-model-parallel-size or use the "
                "replicated FusedAdam.")
        opt = DistributedFusedAdam(lr=ns.lr, weight_decay=0.01)
        opt_state = opt.init(pipe_params)
        ospecs = opt.partition_spec()
    else:
        opt = FusedAdam(lr=ns.lr, weight_decay=0.01)
        opt_state = opt.init(pipe_params)
        ospecs = type(opt_state)(step=P(), m=pspecs, v=pspecs)

    # microbatches are per DATA-rank: local batch = global / dp
    if ns.global_batch_size % dp:
        raise SystemExit(f"--global-batch-size {ns.global_batch_size} "
                         f"not divisible by dp {dp}")
    local_batch = ns.global_batch_size // dp
    if local_batch % ns.micro_batch_size:
        raise SystemExit(
            f"local batch {local_batch} (global/dp) not divisible by "
            f"--micro-batch-size {ns.micro_batch_size} (Megatron errors "
            "here too; silent re-sizing would train a different config)")
    M = local_batch // ns.micro_batch_size
    if pp > 1 and vpp:
        fwd_bwd = forward_backward_pipelining_with_interleaving
    elif pp > 1:
        fwd_bwd = forward_backward_pipelining_without_interleaving
    else:
        fwd_bwd = forward_backward_no_pipelining

    def train_step(p, ostate, batch):
        loss, grads = fwd_bwd(pipe_model, p, batch, num_microbatches=M)
        loss = lax.pmean(loss, ps.DATA_AXIS)
        # tied embedding: the pipeline layout holds the word table twice
        # (embed lookup + LM head); sum the partial grads so both copies
        # take identical updates (Megatron's shared-embedding allreduce)
        grads = accumulate_tied_word_grads(grads)
        # SP: LN/Row-bias grads are per-rank partials over the model axis
        grads = model.allreduce_sequence_parallel_grads(grads)
        if ns.use_distributed_optimizer:
            # ZeRO: rank-local grads in, reduce-scatter inside the step
            p, ostate = opt.step(grads, p, ostate)
        else:
            grads = jax.tree.map(lambda g: lax.pmean(g, ps.DATA_AXIS),
                                 grads)
            p, ostate = opt.step(grads, p, ostate)
        return p, ostate, loss

    bspecs = {"input_ids": P(ps.DATA_AXIS), "labels": P(ps.DATA_AXIS)}
    # donate params + optimizer state (threaded through the loop):
    # halves peak state memory vs keeping input and output copies live
    step = jax.jit(ps.shard_map(
        train_step, mesh=mesh,
        in_specs=(pspecs, ospecs, bspecs),
        out_specs=(pspecs, ospecs, P())), donate_argnums=(0, 1))

    b, s = ns.global_batch_size, ns.seq_length
    for i in range(ns.steps):
        k = jax.random.PRNGKey(1000 + i)
        ids = jax.random.randint(k, (b, s), 0, cfg.vocab_size)
        batch = {"input_ids": ids, "labels": ids}
        pipe_params, opt_state, loss = step(pipe_params, opt_state, batch)
        if i % 2 == 0 or i == ns.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.6f}", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()

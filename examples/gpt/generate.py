#!/usr/bin/env python
"""KV-cached GPT generation CLI — drives ``apex_tpu.serving`` end to
end: bf16 inference params (``amp`` O2 model cast), a preallocated
donated KV cache, bucketed prefill, and continuous batching over a
fixed slot set with greedy or temperature/top-k sampling.

Synthetic weights + prompts (the in-tree models are test-scale); run on
the CPU rig with e.g.::

    python examples/gpt/generate.py --num-requests 8 --num-slots 4 \\
        --max-new-tokens 24 --temperature 0.8 --top-k 50

or pass explicit prompts as comma-separated token ids::

    python examples/gpt/generate.py --prompt 5,7,11 --prompt 42,1,2,3
"""

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/examples/", 1)[0])

from apex_tpu import amp  # noqa: E402
from apex_tpu.models.gpt import GPTConfig, init_gpt  # noqa: E402
from apex_tpu.serving import (  # noqa: E402
    ContinuousBatchingScheduler, DecodeEngine, Request,
)


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    m = p.add_argument_group("model")
    m.add_argument("--vocab-size", type=int, default=512)
    m.add_argument("--hidden-size", type=int, default=64)
    m.add_argument("--num-layers", type=int, default=4)
    m.add_argument("--num-heads", type=int, default=8)
    m.add_argument("--ffn-hidden-size", type=int, default=128)
    m.add_argument("--use-rope", action="store_true")
    m.add_argument("--fp32", action="store_true",
                   help="skip the O2 bf16 model cast (and use an fp32 "
                        "KV cache)")
    s = p.add_argument_group("serving")
    s.add_argument("--num-slots", type=int, default=4)
    s.add_argument("--max-len", type=int, default=128)
    s.add_argument("--top-k", type=int, default=0)
    r = p.add_argument_group("requests")
    r.add_argument("--prompt", action="append", default=None,
                   help="comma-separated token ids; repeatable. Default: "
                        "--num-requests random prompts")
    r.add_argument("--num-requests", type=int, default=8)
    r.add_argument("--max-new-tokens", type=int, default=16)
    r.add_argument("--temperature", type=float, default=0.0)
    r.add_argument("--eos-id", type=int, default=1)
    r.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    ns = parse_args()
    cfg = GPTConfig(
        vocab_size=ns.vocab_size, hidden_size=ns.hidden_size,
        num_layers=ns.num_layers, num_heads=ns.num_heads,
        ffn_hidden_size=ns.ffn_hidden_size,
        max_position_embeddings=ns.max_len, use_rope=ns.use_rope,
        hidden_dropout=0.0)
    params = init_gpt(jax.random.PRNGKey(ns.seed), cfg)
    if not ns.fp32:
        # O2 inference cast: bf16 params (norms stay fp32) — halves
        # weight HBM; the KV cache follows the same dtype choice
        params = amp.initialize("O2", verbosity=0).cast_model(params)
    cache_dtype = jnp.float32 if ns.fp32 else jnp.bfloat16

    engine = DecodeEngine(params, cfg, num_slots=ns.num_slots,
                          max_len=ns.max_len, cache_dtype=cache_dtype,
                          top_k=ns.top_k)
    sched = ContinuousBatchingScheduler(engine, eos_id=ns.eos_id)

    if ns.prompt:
        prompts = [tuple(int(t) for t in s.split(",")) for s in ns.prompt]
    else:
        rng = np.random.RandomState(ns.seed)
        prompts = [
            tuple(int(t) for t in rng.randint(
                2, cfg.vocab_size, size=rng.randint(4, ns.max_len // 2)))
            for _ in range(ns.num_requests)]

    for i, prompt in enumerate(prompts):
        sched.submit(Request(prompt=prompt,
                             max_new_tokens=ns.max_new_tokens,
                             temperature=ns.temperature,
                             seed=ns.seed + i))

    t0 = time.perf_counter()
    outputs = sched.run()
    dt = time.perf_counter() - t0
    n_tok = sum(len(o) for o in outputs)
    for i, (prompt, out) in enumerate(zip(prompts, outputs)):
        print(f"[{i}] prompt({len(prompt)})={list(prompt)[:8]}... "
              f"-> {out}")
    print(f"generated {n_tok} tokens across {len(outputs)} requests "
          f"in {dt:.2f}s ({n_tok / dt:.1f} tok/s, includes compile)")


if __name__ == "__main__":
    main()
